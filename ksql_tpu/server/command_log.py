"""Command log: the metadata WAL.

Analog of the reference's command topic machinery
(ksqldb-rest-app/.../computation/: CommandStore.java:65, CommandTopic.java:37,
CommandRunner.java:63, InteractiveStatementExecutor.java:58,
CommandTopicBackupImpl.java:46).  All DDL/DML statements that mutate cluster
state are appended to a single-partition durable log and re-executed on every
node; startup replays the whole log to rebuild the engine (the
recovery/bootstrap path, CommandRunner.processPriorCommands:260).

The log is file-backed JSONL (the CommandTopicBackup is the primary here —
there is no external Kafka); an in-memory variant backs tests.  Writes are
atomic appends under a lock with fsync, replicating the transactional
producer's guarantee (DistributingExecutor.java:197-236) that commands are
totally ordered and never interleaved.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ksql_tpu.common import faults
from ksql_tpu.common.errors import KsqlException


@dataclasses.dataclass
class Command:
    """QueuedCommand analog: one durable DDL/DML statement."""

    seq: int
    statement: str
    session_properties: Dict[str, Any]
    timestamp_ms: int
    version: int = 1

    def to_json(self) -> Dict[str, Any]:
        return {
            "seq": self.seq,
            "statement": self.statement,
            "sessionProperties": self.session_properties,
            "timestampMs": self.timestamp_ms,
            "version": self.version,
        }

    @staticmethod
    def from_json(obj: Dict[str, Any]) -> "Command":
        return Command(
            seq=int(obj["seq"]),
            statement=obj["statement"],
            session_properties=obj.get("sessionProperties", {}),
            timestamp_ms=int(obj.get("timestampMs", 0)),
            version=int(obj.get("version", 1)),
        )


class CommandLog:
    """Durable, totally-ordered command log (CommandStore + CommandTopic)."""

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._lock = threading.RLock()
        self._commands: List[Command] = []
        self._fh = None
        # set when a torn write killed this instance: accepting further
        # appends would acknowledge commands that can never be durable
        self._dead = False
        if path:
            if os.path.exists(path):
                self._load(path)
            self._fh = open(path, "a")

    def _load(self, path: str) -> None:
        """Replay the JSONL file.  A torn FINAL line — the signature of a
        crash mid-append (a partial single-line write, so no trailing
        newline) — is tolerated by truncating the file at the tear.  Any
        other unparseable line is real damage and raises (the
        CommandRunner degraded/corruption-detection analog): appends are
        newline-terminated single writes, so a complete line that fails to
        parse cannot be a tear."""
        tear_at = None  # byte offset of the torn final line
        offset = 0
        with open(path, "rb") as f:
            for raw in f:
                line_start = offset
                offset += len(raw)
                line = raw.decode("utf-8", errors="replace").strip()
                if not line:
                    continue
                try:
                    cmd = Command.from_json(json.loads(line))
                except (ValueError, KeyError) as e:
                    if not raw.endswith(b"\n"):
                        # an unterminated line is by construction the last
                        # in the file: the tail tear
                        tear_at = line_start
                        break
                    raise KsqlException(
                        f"Corrupt command log at {path}: {e}"
                    ) from e
                self._commands.append(cmd)
        if tear_at is not None:
            with open(path, "r+b") as f:
                f.truncate(tear_at)

    # ---------------------------------------------------------------- write
    def append(self, statement: str, session_properties: Optional[Dict] = None) -> Command:
        with self._lock:
            if self._dead:
                # acknowledging an append a torn write can't persist would
                # lose the command on restart — refuse until reopened
                raise KsqlException(
                    f"command log at {self._path} is dead after a torn "
                    "write; reopen to recover"
                )
            cmd = Command(
                seq=len(self._commands),
                statement=statement,
                session_properties=dict(session_properties or {}),
                timestamp_ms=int(time.time() * 1000),
            )
            if self._fh is not None:
                line = json.dumps(cmd.to_json(), separators=(",", ":")) + "\n"
                # corrupt-mode rules tear the line (torn-write simulation);
                # raise-mode fails the append before anything lands
                line = faults.fault_point("commandlog.append", self._path or "", line)
                if not line.endswith("\n"):
                    # a torn write only exists mid-crash: persist the tear
                    # and declare this log instance dead, so no later append
                    # can concatenate onto the torn line (which would make
                    # _load()'s tail truncation swallow acknowledged
                    # commands).  Reopening recovers via truncate-at-tear.
                    self._fh.write(line)
                    self._fh.flush()
                    self._fh.close()
                    self._fh = None
                    self._dead = True
                    raise KsqlException(
                        f"command log torn at {self._path}: append failed"
                    )
                pos = self._fh.tell()
                try:
                    self._fh.write(line)
                    self._fh.flush()
                    # a fault here models the crash-after-write-before-fsync
                    # window: the line may be durable, torn, or lost —
                    # exactly what _load()'s torn-tail tolerance recovers from
                    faults.fault_point("commandlog.fsync", self._path or "")
                    os.fsync(self._fh.fileno())
                except Exception:
                    # roll the partial append back so the durable log and the
                    # in-memory view stay in step (seq must never repeat); a
                    # hard crash here instead leaves a torn tail for _load()
                    try:
                        self._fh.seek(pos)
                        self._fh.truncate(pos)
                    except OSError:
                        pass
                    raise
            self._commands.append(cmd)
            return cmd

    # ----------------------------------------------------------------- read
    def read_from(self, seq: int) -> List[Command]:
        with self._lock:
            return list(self._commands[seq:])

    def end_seq(self) -> int:
        with self._lock:
            return len(self._commands)

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def compact(commands: List[Command]) -> List[Command]:
    """RestoreCommandsCompactor analog: drop create/drop pairs and terminated
    queries so replay doesn't thrash.  Conservative: only removes a CREATE
    when a later DROP names the same object and nothing in between reads it."""
    dropped: Dict[str, int] = {}
    out: List[Command] = []
    import re

    for i, cmd in enumerate(commands):
        m = re.match(r"\s*DROP\s+(?:STREAM|TABLE)\s+(?:IF\s+EXISTS\s+)?([A-Za-z_0-9`]+)",
                     cmd.statement, re.I)
        if m:
            dropped[m.group(1).strip("`").upper()] = i
    for i, cmd in enumerate(commands):
        m = re.match(
            r"\s*CREATE\s+(?:OR\s+REPLACE\s+)?(?:SOURCE\s+)?(?:STREAM|TABLE)\s+"
            r"(?:IF\s+NOT\s+EXISTS\s+)?([A-Za-z_0-9`]+)",
            cmd.statement, re.I)
        if m:
            name = m.group(1).strip("`").upper()
            drop_at = dropped.get(name)
            if drop_at is not None and drop_at > i:
                continue  # superseded by a later drop
        out.append(cmd)
    return out


class CommandRunner:
    """Replays prior commands on startup and applies new ones
    (CommandRunner.java:63: processPriorCommands:260 + fetchAndRunCommands:315).
    """

    def __init__(self, log: CommandLog, execute: Callable[[Command], None]):
        self.log = log
        self.execute = execute
        self.position = 0
        self.degraded = False
        self._lock = threading.RLock()
        # seqs applied out-of-band (the local node executes its own
        # statements inline for the response): the tail loop skips them
        self._applied_out_of_band: set = set()
        self._retries: dict = {}

    def process_prior_commands(self) -> int:
        """Bootstrap: compact + replay the whole log. Returns commands run."""
        cmds = compact(self.log.read_from(0))
        n = 0
        for cmd in cmds:
            try:
                faults.fault_point("command.runner.execute", cmd.statement)
                self.execute(cmd)
                n += 1
            except Exception:
                # reference logs and continues on replay errors of individual
                # commands (they may legitimately fail, e.g. topic missing)
                continue
        self.position = self.log.end_seq()
        return n

    #: attempts before a persistently-failing peer command is skipped and
    #: the runner marks itself degraded (CommandRunner DEGRADED state)
    MAX_COMMAND_RETRIES = 3

    def _apply_one(self, cmd: Command) -> bool:
        """Shared failure policy: returns True when ``position`` may advance
        past ``cmd``.  Deterministic user errors (KsqlException family) are
        skipped outright — they were validated on the issuing node, and the
        reference logs-and-continues on replay failures of that class.
        Other failures retry up to MAX_COMMAND_RETRIES ticks, then the
        runner degrades and skips."""
        from ksql_tpu.common.errors import KsqlException

        try:
            # chaos seam (peer statement chaos): an injected raise is an
            # infra failure — bounded retries, then degraded-and-skip
            faults.fault_point("command.runner.execute", cmd.statement)
            self.execute(cmd)
        except KsqlException:
            return True  # deterministic statement error: skip, stay healthy
        except Exception:  # noqa: BLE001 — infra error: bounded retries
            tries = self._retries.get(cmd.seq, 0) + 1
            self._retries[cmd.seq] = tries
            if tries < self.MAX_COMMAND_RETRIES:
                return False
            self.degraded = True
        self._retries.pop(cmd.seq, None)
        return True

    def fetch_and_run(self) -> int:
        """Poll loop body: run any newly appended commands (peer statements
        on a shared log included; locally-executed seqs are skipped)."""
        with self._lock:
            cmds = self.log.read_from(self.position)
            n = 0
            for cmd in cmds:
                if cmd.seq in self._applied_out_of_band:
                    self._applied_out_of_band.discard(cmd.seq)
                    self.position = cmd.seq + 1
                    continue
                if not self._apply_one(cmd):
                    break  # keep position: retry this command next tick
                n += 1
                self.position = cmd.seq + 1
            return n

    def catch_up_to(self, seq: int) -> None:
        """Apply every pending command BEFORE ``seq`` — a distributing node
        serializes against peers' earlier statements before executing its
        own (DistributingExecutor waits on the command queue this way).  A
        transiently-failing peer command keeps ``position`` so the tail
        loop retries it; the caller's own seq is tracked out-of-band."""
        with self._lock:
            for cmd in self.log.read_from(self.position):
                if cmd.seq >= seq:
                    break
                if cmd.seq not in self._applied_out_of_band:
                    if not self._apply_one(cmd):
                        return  # retried by fetch_and_run; proceed locally
                else:
                    self._applied_out_of_band.discard(cmd.seq)
                self.position = cmd.seq + 1

    def mark_applied(self, seq: int) -> None:
        """Record that ``seq`` was executed inline by this node."""
        with self._lock:
            if self.position == seq:
                self.position = seq + 1
            else:
                self._applied_out_of_band.add(seq)
