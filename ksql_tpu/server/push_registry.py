"""Push registry: multiplex push sessions as filtered taps over shared
persistent pipelines.

The engine seam in the reference splits ``executeScalablePushQuery`` from
per-session transient queries (KsqlEngine.java:558 / ScalablePushRegistry)
because one-executor-per-subscriber cannot serve high fan-out: a million
subscribers to the same stream must not mean a million redundant
consumer + executor pipelines re-decoding the same topic.  This module is
that serving architecture:

* the FIRST push query of a given canonical shape (source + shared
  pre-ops; the per-session residual is excluded from the key) spins up ONE
  shared internal pipeline — an identity query over the source, built
  through the same device→oracle executor ladder persistent queries use —
  that materializes a bounded in-memory changelog ring of offset-stamped
  emissions;
* every subsequent compatible session becomes a cheap **tap**: a
  per-session residual (WHERE predicate + projection, the exact oracle
  ``FilterNode``/``SelectNode`` a dedicated session would run) evaluated
  host-side against the shared emissions, with a per-tap cursor into the
  ring;
* a slow tap that falls off the ring's tail is resumed past the gap with a
  gap marker naming the skipped offset span (the PR-5 gap-marker
  contract) — it never stalls the shared pipeline and never dies;
* a shared-pipeline fault self-heals exactly like a supervised session
  (classify → rewind → rebuild → backoff on the ``ksql.query.retry.*``
  knobs) and the heal lands ONE in-ring gap marker every tap observes at
  its own cursor position;
* the last tap detaching starts the ``ksql.push.registry.linger.ms``
  clock; an expired idle pipeline is reaped (refcounted teardown), an
  attach inside the window reuses the warm pipeline and its ring.

Two pipeline modes:

* **listener** — when a RUNNING persistent query materializes the source,
  the pipeline subscribes one callback through the engine's
  ``register_push_tap`` seam and fans its fence-guarded ``on_emit``
  emissions out to the taps (PR-6 zombie fencing applies unchanged: a
  fenced-off executor can never write the ring).  A terminated upstream
  fails the pipeline over to standalone mode with a gap marker.
* **standalone** — the pipeline owns a latest-offset consumer over the
  source topic and an executor built like the transient device path
  (device when the identity plan lowers, oracle otherwise; sink muted).
  All ``device.compile`` work happens HERE, once, on the shared pipeline's
  flight recorder — taps compile nothing.

Locking: one registry-wide RLock guards pipelines, rings, tap tables and
counters.  Lock order is engine_lock → registry lock everywhere (tap polls
run under the server's engine_lock; ``close()`` takes only the registry
lock and never the engine's).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ksql_tpu.common import config as cfg
from ksql_tpu.common import faults, tracing
from ksql_tpu.execution import expressions as ex
from ksql_tpu.execution import steps as st
from ksql_tpu.server.tap_kernel import (
    ResidualUnsupported,
    TapKernel,
    classify_residual,
)

#: ring entry kinds
ROW = 0
GAP = 1

#: pseudo-columns bound to the source record's topic position — the shared
#: emit stream does not carry them, so residuals referencing them keep a
#: dedicated session
_POSITIONAL_PSEUDO = ("ROWPARTITION", "ROWOFFSET")


def _now_ms() -> float:
    return time.time() * 1000.0


def residual_chain(plan) -> Optional[List[Any]]:
    """Classify a push-query plan for sharing: returns the step chain
    ``[root-side residual steps..., StreamSource]`` when the plan is a
    shareable shape — an optional sink over any number of
    StreamSelect/StreamFilter steps terminating in exactly a StreamSource —
    else None (aggregates, joins, windows, repartitions and table
    functions are stateful/positional residuals that keep a dedicated
    session)."""
    step = plan.physical_plan
    if isinstance(step, (st.StreamSink, st.TableSink)):
        step = step.source
    chain: List[Any] = []
    while isinstance(step, (st.StreamSelect, st.StreamFilter)):
        chain.append(step)
        step = step.source
    if type(step) is not st.StreamSource:
        return None
    for s in chain:
        exprs = (
            [s.predicate] if isinstance(s, st.StreamFilter)
            else [e for _, e in s.selects]
        )
        for e0 in exprs:
            for node in ex.walk(e0):
                if isinstance(node, ex.ColumnRef) and (
                    node.name in _POSITIONAL_PSEUDO
                ):
                    return None
    chain.append(step)
    return chain


class PushTap:
    """One session's subscription to a shared pipeline: a cursor into the
    ring plus the session's residual filter/projection nodes (the same
    oracle nodes a dedicated session would run, compiled once at attach).

    Delivery happens on the polling session's thread; per-tap state
    (cursor, counters) is written under the registry lock because the
    listener-mode emit path publishes ring entries concurrently."""

    def __init__(self, pipeline: "SharedPushPipeline", session,
                 residual_steps: List[Any]):
        from ksql_tpu.runtime.oracle import Compiler, FilterNode, SelectNode

        self.pipeline = pipeline
        self.session = session
        self.id = session.id
        engine = pipeline.engine
        compiler = Compiler(
            engine.registry,
            lambda expr, exc: engine._on_error(
                f"push-tap:{session.id}:{expr}", exc
            ),
        )
        # residual_steps is root-side-first; events flow source-side-first
        nodes = []
        for s in reversed(residual_steps):
            if isinstance(s, st.StreamFilter):
                nodes.append(FilterNode(s, compiler, is_table=False))
            else:
                nodes.append(SelectNode(s, compiler))
        self._nodes = nodes
        # projection-only view of the chain: fused delivery applies these
        # to rows the device mask already passed (filters skipped — the
        # kernel evaluated them), reproducing the oracle transform exactly
        self._select_nodes = [
            n for n in nodes if isinstance(n, SelectNode)
        ]
        # fused residual classification (ISSUE 12): join the pipeline's
        # predicate family when the WHERE chain lowers; unsupported
        # residuals keep the host path with the reason counted under
        # engine.fallback_reasons (the windowing_fallback contract).
        # Pure projections (no WHERE) stay host-side silently: with
        # nothing to filter, delivery already IS a plain gather.
        self.fused = False
        self.fused_fallback: Optional[str] = None
        kernel = pipeline.ensure_kernel()
        if kernel is not None:
            try:
                spec = classify_residual(
                    residual_steps, pipeline.out_schema
                )
                if spec is not None:
                    kernel.attach(session.id, spec)
                    self.fused = True
            except ResidualUnsupported as e:
                self.fused_fallback = str(e)
                reason = f"push residual stays host-side: {e}"
                engine.fallback_reasons[reason] = (
                    engine.fallback_reasons.get(reason, 0) + 1
                )
        self.cursor = pipeline.head_seq()  # attach at the live head
        self.delivered_rows = 0
        self.evicted_rows = 0
        self.gap_markers = 0
        self.closed = False

    def lag(self) -> int:
        """Ring rows published but not yet drained by this tap — the
        per-tap backpressure gauge ``/query-lag/<id>`` serves."""
        return max(self.pipeline.head_seq() - self.cursor, 0)

    # thread entrypoint: tap delivery — runs on whichever thread polls the
    # owning session (the server's HTTP handler threads), concurrently
    # with the listener-mode emit path appending to the shared ring
    # graftlint: entrypoint=push-tap-poll
    def poll(self) -> None:
        """Advance the shared pipeline, then deliver new emissions through
        this tap's residual into the owning session (rows via the
        session's ``_on_emit``, gap markers via ``_enqueue_gap``)."""
        pipe = self.pipeline
        pipe.advance()
        max_rows = int(pipe.engine.effective_property(
            cfg.PUSH_REGISTRY_MAX_POLL_ROWS, 4096
        ))
        # overload tap-clamp: while engaged, every tap drains in small
        # slices so N storming subscribers cannot monopolize the engine
        # (lock-free read — the clamp seam never takes the manager lock
        # under the registry lock)
        max_rows = pipe.engine.overload.tap_poll_rows(max_rows)
        entries, evicted, new_cursor = pipe.read_from(self.cursor, max_rows)
        if not entries and evicted is None:
            # idle poll: nothing to deliver, and an idle-poll trace would
            # be discarded anyway (keep=False) — skip the TickTrace
            # allocation + recorder lock entirely on the quiet-source
            # hot path 50 polling taps sit on
            self.cursor = new_cursor  # graftlint: owner=push-tap-poll
            return
        fused = None
        if self.fused and entries and pipe.kernel is not None:
            # ONE kernel evaluation per span serves every fused tap: the
            # span cache keys on (start, rows, membership epoch), so taps
            # polling in lockstep share the same bitmask pass.  None =
            # degraded/below-min-taps/uncached-failure: host path.
            fused = pipe.kernel.mask_for(
                self.id, new_cursor - len(entries), entries
            )
        # delivery ticks go to a SEPARATE "<pipeline>/taps" recorder: N
        # taps per pump would otherwise evict the pump's own ticks from
        # the 64-slot ring and reduce the (gated) push.pipeline.step p99
        # to a near-single-sample statistic under fan-out
        rec = pipe.engine.recorder_if_enabled(pipe.id + "/taps")
        with tracing.tick(rec):
            with tracing.span("push.tap.deliver"):
                delivered = self._deliver(entries, evicted, fused)
            # ring lag sampled once per delivering poll (sum over the
            # window / n = mean lag; the point-in-time gauge rides
            # /query-lag)
            tracing.counter(
                "push.tap.deliver", rows=delivered,
                ring_lag=max(pipe.head_seq() - new_cursor, 0),
            )
        self.cursor = new_cursor  # graftlint: owner=push-tap-poll

    def _deliver(self, entries, evicted, fused=None) -> int:
        """Deliver ``entries`` into the owning session — through the fused
        kernel's precomputed match bitmask when ``fused`` is set (a
        bitmask read + column gather: only matching rows pay host-side
        projection), else through the host residual chain row-at-a-time.
        Gap markers deliver identically on both paths; returns rows
        delivered."""
        from ksql_tpu.runtime.oracle import SinkEmit, StreamRow

        pipe = self.pipeline
        sess = self.session
        registry = pipe.registry
        if evicted is not None:
            # fell off the ring's tail: resume past the gap, never stall
            # the shared pipeline (PR-5 contract — span, not silence).
            # skippedRows counts ROWS (evicted markers excluded), so it
            # sums consistently with ksql_push_registry_ring_evicted_total
            skipped = evicted[2]
            marker = {
                "queryId": sess.id,
                "pipeline": pipe.id,
                "evicted": True,
                "fromSeq": evicted[0],
                "toSeq": evicted[1],
                "skippedRows": skipped,
                "error": (
                    f"tap lagged {skipped} rows past the shared ring "
                    f"(ksql.push.registry.ring.size={pipe.ring_size}); "
                    "resuming at the retained tail"
                ),
            }
            with registry._lock:
                self.evicted_rows += skipped
                self.gap_markers += 1
                registry.gap_markers += 1
            sess._enqueue_gap(marker)
        delivered = 0
        prog = getattr(sess, "progress", None)
        if fused is not None:
            # fused path: the kernel already evaluated every filter over
            # the whole span; visit only matching rows (+ interleaved gap
            # markers, in ring order).  The watermark advances once by the
            # span's max event time — the same fold the per-row path
            # reaches, without O(rows) Python.
            if prog is not None and fused["max_ts"] is not None:
                prog.note_watermark(fused["max_ts"])
            positions = np.flatnonzero(fused["mask"][: len(entries)])
            limit = getattr(sess, "limit", None)
            if limit is not None:
                # LIMIT-aware gather: don't even visit matches past the
                # session's remaining budget (the session still enforces
                # the cap authoritatively in _on_emit)
                remaining = max(int(limit) - int(sess._results), 0)
                positions = positions[:remaining]
            gap_positions = [
                i for i, (k, _) in enumerate(entries) if k == GAP
            ]
            if gap_positions:
                positions = sorted(set(positions.tolist()) | set(gap_positions))
            index_iter = positions
        else:
            index_iter = range(len(entries))
        for i in index_iter:
            kind, payload = entries[i]
            if kind == GAP:
                marker = dict(payload)
                marker["queryId"] = sess.id
                with registry._lock:
                    self.gap_markers += 1
                    registry.gap_markers += 1
                sess._enqueue_gap(marker)
                continue
            key, row, ts = payload
            if fused is not None:
                # mask passed: apply the projection chain only (filters
                # are already decided) to this matching row
                events: List[Any] = [StreamRow(key, row, ts, None)]
                for node in self._select_nodes:
                    events = [
                        ev2 for ev in events for ev2 in node.receive(0, ev)
                    ]
            else:
                if prog is not None:
                    # the tracker sees every shared emission (filtered-out
                    # rows still advance the tap's event-time watermark)
                    prog.note_watermark(ts)
                events = [StreamRow(key, row, ts, None)]
                for node in self._nodes:
                    nxt: List[Any] = []
                    for ev in events:
                        nxt.extend(node.receive(0, ev))
                    events = nxt
                    if not events:
                        break
            for ev in events:
                if sess._on_emit(SinkEmit(ev.key, ev.row, ev.ts, ev.window)):
                    delivered += 1
        if delivered:
            with registry._lock:
                self.delivered_rows += delivered
                registry.delivered_rows += delivered
        return delivered

    def close(self) -> None:
        if self.closed:
            return
        self.closed = True
        if self.fused and self.pipeline.kernel is not None:
            # lane free is a mask update — no retrace for the survivors
            self.pipeline.kernel.detach(self.id)
        self.pipeline.detach(self)


class SharedPushPipeline:
    """ONE internal pipeline serving every tap of a canonical shape: an
    identity query over the source materializing a bounded changelog ring
    of (key, full row, ts) emissions, offset-stamped by a monotone
    sequence.  See the module docstring for modes and healing."""

    def __init__(self, registry: "PushRegistry", key: str, source_name: str):
        self.registry = registry
        self.engine = registry.engine
        self.key = key
        self.id = f"pushreg_{next(registry._seq)}_{source_name.lower()}"
        self.source_name = source_name
        self._lock = registry._lock
        self.ring: List[Tuple[int, Any]] = []
        self.base_seq = 0
        # seqs of GAP entries that were evicted off the ring (bounded):
        # subtracts markers from lagging taps' skipped-ROW spans
        self._evicted_gap_seqs: List[int] = []
        self.ring_size = int(self.engine.effective_property(
            cfg.PUSH_REGISTRY_RING_SIZE, 8192
        ))
        self.taps: Dict[str, PushTap] = {}
        self.idle_since_ms: Optional[float] = None
        self.stopped = False
        # self-healing bookkeeping (the session ladder, pipeline-scoped)
        self.restart_count = 0
        self.retry_at_ms = 0.0
        self.retry_backoff_ms = 0.0
        self.terminal = False
        self._needs_rebuild = False
        # mode wiring
        self.mode = "standalone"
        self.upstream_qid: Optional[str] = None
        self._unsubscribe: Optional[Callable] = None
        self.consumer = None
        self.executor = None
        self.backend = "none"
        self._planned = None
        self._key_names: List[str] = []
        # fused tap residuals (ISSUE 12): the batched predicate kernel
        # (built lazily on the first compilable tap) + listener-mode
        # device emission blocks, keyed by their ring-seq span so the
        # kernel evaluates device-resident columns instead of re-encoding
        # host rows
        self.kernel: Optional[TapKernel] = None
        self.out_schema = None
        self._emit_blocks: deque = deque(maxlen=8)
        # block held between a batch callback and its last row append
        # ([start, n, blk, appended]) — committed only once complete
        self._pending_block: Optional[list] = None
        fused_on = cfg._bool(self.engine.effective_property(
            cfg.PUSH_FUSED_ENABLE, True
        ))
        attached = self.engine.register_push_tap(
            source_name, self._on_emit,
            # only a fused pipeline consumes emit blocks: without the
            # kernel the upstream must not pay per-batch device gathers
            batch_cb=self._on_emit_batch if fused_on else None,
        )
        if attached is not None:
            # listener mode: ride the running query's fence-guarded
            # on_emit fan-out — one listener for N taps
            self.upstream_qid, self._unsubscribe = attached
            self.mode = "listener"
            src = self.engine.metastore.get_source(source_name)
            self._key_names = (
                [c.name for c in src.schema.key_columns] if src else []
            )
            self.out_schema = src.schema if src else None
        else:
            self._build_standalone(from_beginning=False)

    # ------------------------------------------------------------- building
    def _build_standalone(self, from_beginning: bool) -> None:
        """Plan + build the internal identity pipeline over the source
        (the shared common prefix: consume + decode + identity
        projection), consuming from the topic's current end."""
        from ksql_tpu.analyzer.analyzer import analyze_query
        from ksql_tpu.runtime.topics import Consumer

        engine = self.engine
        prepared = engine.parse(
            f"SELECT * FROM {self.source_name} EMIT CHANGES;"
        )
        analysis = analyze_query(
            prepared[0].statement, engine.metastore, engine.registry
        )
        self._planned = engine.planner.plan(analysis, self.id)
        out_schema = self._planned.plan.physical_plan.schema
        with self._lock:
            # the emit path reads the key layout: swap it under the lock
            # (a listener-mode zombie emit may still race the failover)
            self._key_names = [c.name for c in out_schema.key_columns]
            self.out_schema = out_schema
        topics = sorted({
            step.topic
            for step in st.walk_steps(self._planned.plan.physical_plan)
            if hasattr(step, "topic")
            and not isinstance(step, (st.StreamSink, st.TableSink))
        })
        for t in topics:
            engine.broker.create_topic(t)
        self.consumer = Consumer(
            engine.broker, topics, from_beginning=from_beginning
        )
        self.executor = self._build_executor()
        self.mode = "standalone"

    def _build_executor(self):
        """The transient executor ladder: device when the identity plan
        lowers (ALL compile work lands here, on the one shared pipeline),
        oracle otherwise.  The sink is muted — the ring is the output."""
        from ksql_tpu.runtime.oracle import OracleExecutor

        engine = self.engine
        executor = None
        backend = str(
            engine.effective_property(cfg.RUNTIME_BACKEND, "device")
        ).lower()
        if backend != "oracle":
            from ksql_tpu.compiler.jax_expr import DeviceUnsupported
            from ksql_tpu.runtime.device_executor import DeviceExecutor

            device_plan = engine._wrap_transient_plan(
                self._planned.plan, self.id
            )
            try:
                executor = DeviceExecutor(
                    device_plan, engine.broker, engine.registry,
                    on_error=engine._on_error, emit_callback=self._on_emit,
                    batch_size=int(engine.config.get(cfg.BATCH_CAPACITY)),
                    per_record=True,  # taps expect per-record emit order
                    store_capacity=int(engine.config.get(cfg.STATE_SLOTS)),
                )
                self.backend = "device"
            except DeviceUnsupported:
                pass
            except Exception as e:  # noqa: BLE001 — compile failure must
                engine._on_error(f"push-registry:{self.id}", e)  # not kill
        if executor is None:
            engine.annotate_serde_semantics(self._planned.plan)
            executor = OracleExecutor(
                self._planned.plan, engine.broker, engine.registry,
                on_error=engine._on_error, emit_callback=self._on_emit,
            )
            self.backend = "oracle"
        writer = getattr(executor, "sink_writer", None)
        if writer is not None:
            writer.enabled = False  # the ring is the only output
        return executor

    # ------------------------------------------------------- fused kernel
    def ensure_kernel(self) -> Optional[TapKernel]:
        """The pipeline's fused residual kernel (tap_kernel.py), built
        lazily on the first compilable tap — None when the feature is off
        or the output schema is unknown (listener over an unregistered
        source)."""
        with self._lock:
            if self.kernel is not None:
                return self.kernel
            engine = self.engine
            if self.out_schema is None or not cfg._bool(
                engine.effective_property(cfg.PUSH_FUSED_ENABLE, True)
            ):
                return None
            self.kernel = TapKernel(
                self, self.out_schema, self._lock,
                capacity_min=int(engine.effective_property(
                    cfg.PUSH_FUSED_CAPACITY_MIN, 8
                )),
                capacity_max=int(engine.effective_property(
                    cfg.PUSH_FUSED_CAPACITY_MAX, 4096
                )),
                min_taps=int(engine.effective_property(
                    cfg.PUSH_FUSED_MIN_TAPS, 2
                )),
            )
            return self.kernel

    # thread entrypoint: fires with the per-emit listener fan-out below,
    # once per decoded device batch, from the engine's process thread
    # graftlint: entrypoint=push-pipeline-emit
    def _on_emit_batch(self, emits, blk) -> None:
        """Listener-mode batch handoff: hold the upstream device
        executor's still-device-resident columnar emit block PENDING for
        the ring-seq span the per-emit appends right after this call will
        occupy — the tap kernel then evaluates residuals straight over the
        block instead of re-encoding host rows.

        The block only commits to ``_emit_blocks`` after all n rows
        actually appended (``_on_emit`` counts them down): if the
        upstream's emit fence flips mid-dispatch, the dropped tail's seqs
        are later occupied by the REBUILT executor's rows, and a block
        committed eagerly would hand the kernel the OLD executor's
        columns for them.  An incomplete batch simply never commits."""
        if blk is None:
            return
        with self._lock:
            if self.stopped or self.kernel is None:
                self._pending_block = None
                return  # no fused consumer: don't retain device arrays
            start = self.base_seq + len(self.ring)
            # [start seq, expected rows, block, rows appended so far]
            self._pending_block = [start, len(emits), blk, 0]

    # ------------------------------------------------------------ emission
    # thread entrypoint: in listener mode this fires from whichever thread
    # drives engine.poll_once (the server's process loop), concurrently
    # with tap HTTP threads reading the ring
    # graftlint: entrypoint=push-pipeline-emit
    def _on_emit(self, e) -> None:
        """Shared emit fan-in: stamp the emission with the next ring seq.
        The full row (key columns merged in, oracle decode layout) is what
        tap residuals evaluate against."""
        # ring-append accounting on the active tick — in listener mode the
        # active trace is the UPSTREAM query's, so its flight recorder (and
        # /query-trace) shows the fan-out rows its emissions feed; in
        # standalone mode this lands inside the pipeline's own
        # push.pipeline.step span (rows counter, no extra ms)
        tracing.counter("push.pipeline.step", rows=1)
        if e.row is None:
            row = None
        else:
            row = dict(zip(self._key_names, e.key))
            row.update(e.row)
        with self._lock:
            if self.stopped:
                return  # reaped pipeline: drop the stale emission
            seq = self.base_seq + len(self.ring)
            self.ring.append((ROW, (e.key, row, e.ts)))
            pend = self._pending_block
            if pend is not None:
                if seq == pend[0] + pend[3]:
                    pend[3] += 1
                    if pend[3] == pend[1]:
                        # every row of the batch landed: the block is
                        # provably aligned with these ring seqs — commit
                        self._emit_blocks.append(
                            (pend[0], pend[1], pend[2])
                        )
                        self._pending_block = None
                else:  # out-of-band append: the pending block can no
                    self._pending_block = None  # longer be trusted
            overflow = len(self.ring) - self.ring_size
            if overflow > 0:
                evicted_rows = 0
                for off, (k, _) in enumerate(self.ring[:overflow]):
                    if k == ROW:
                        evicted_rows += 1
                    else:
                        # remember evicted GAP seqs so a lagging tap's
                        # skipped-span accounting can subtract them —
                        # skippedRows must mean ROWS, matching the
                        # registry's ring-evicted counter
                        self._evicted_gap_seqs.append(self.base_seq + off)
                del self.ring[:overflow]
                self.base_seq += overflow
                if len(self._evicted_gap_seqs) > 256:
                    # bounded memory; gaps are one-per-incident rare.  A
                    # truncated entry can only OVERSTATE a span's row
                    # count by one, never hide a lost row.
                    del self._evicted_gap_seqs[:-256]
                self.registry.ring_evicted += evicted_rows

    def head_seq(self) -> int:
        with self._lock:
            return self.base_seq + len(self.ring)

    def read_from(self, cursor: int, max_rows: int):
        """Ring entries from ``cursor`` (bounded), the evicted span if the
        cursor fell off the tail — ``(from_seq, to_seq, skipped_rows)``
        with gap-marker entries excluded from the row count — and the new
        cursor."""
        with self._lock:
            evicted = None
            if cursor < self.base_seq:
                gaps_in_span = sum(
                    1 for s in self._evicted_gap_seqs
                    if cursor <= s < self.base_seq
                )
                evicted = (
                    cursor, self.base_seq,
                    max(self.base_seq - cursor - gaps_in_span, 0),
                )
                cursor = self.base_seq
            start = cursor - self.base_seq
            entries = list(self.ring[start:start + max_rows])
            return entries, evicted, cursor + len(entries)

    def _append_gap(self, marker: Dict[str, Any]) -> None:
        with self._lock:
            self._pending_block = None  # a gap entry breaks the span
            self.ring.append((GAP, dict(marker)))
            # gap markers never evict here: the next row append rebounds
            # the ring, and a marker is one entry per incident

    # ------------------------------------------------------------- driving
    def advance(self, max_records: int = 1024) -> None:
        """Pump the shared pipeline (called by every tap poll; serialized
        under the server's engine lock).  Listener mode nudges the engine
        loop; standalone mode polls its own consumer through the executor
        with the session self-healing ladder around it.

        Each pump is bounded by the ring size: a tap that polls keeps up
        with its own advances by construction — only a tap that stops
        polling while OTHERS drive the pipeline falls off the tail."""
        if self.terminal or self.stopped:
            return
        max_records = max(1, min(max_records, self.ring_size))
        engine = self.engine
        if self._now() < self.retry_at_ms:
            return  # backing off after a heal (failover retries included)
        if self.mode == "listener":
            h = engine.queries.get(self.upstream_qid)
            if h is None or not h.is_running():
                # upstream terminated/paused: fail over to a standalone
                # consumer at the live end, with a gap marker naming it.
                # One regime change per advance — the next poll drains
                # (and a FAILED failover must not fall through to the
                # rebuild branch and double-count the incident)
                self._failover_standalone()
            else:
                engine.run_until_quiescent(max_iters=1)
            return
        if self._needs_rebuild:
            try:
                if self.consumer is None or self._planned is None:
                    # a failed failover left no pipeline at all: rebuild
                    # the whole standalone side, not just the executor
                    self._build_standalone(from_beginning=False)
                else:
                    self.executor = self._build_executor()
                self._needs_rebuild = False
            except Exception as e:  # noqa: BLE001 — still failing: another
                self._failed(e, dict(self.consumer.positions)  # incident
                             if self.consumer is not None else {})
                return
        snapshot = dict(self.consumer.positions)
        rec = engine.recorder_if_enabled(self.id)
        try:
            # chaos seam: kill/hang the SHARED pipeline under many taps
            # (scripts/chaos_soak.py --fanout)
            faults.fault_point("push.pipeline.step", self.id)
            with tracing.tick(rec) as tick:
                with tracing.span("push.pipeline.step"):
                    records = self.consumer.poll(max_records)
                    if tick is not None:
                        tick.keep = bool(records)
                    for topic, r in records:
                        try:
                            self.executor.process(topic, r)
                        except Exception as pe:  # noqa: BLE001
                            if engine._is_poison(pe):
                                engine._on_error(
                                    f"poison:{self.id}:{topic}", pe
                                )
                                continue
                            raise
                    drain = getattr(self.executor, "drain", None)
                    if drain is not None:
                        drain()
            if records and self.restart_count:
                # healthy rows after a restart close the incident: the
                # retry budget bounds restarts PER incident, not over the
                # pipeline's lifetime (the session ladder's contract)
                self.restart_count = 0
                self.retry_backoff_ms = 0.0
        except Exception as e:  # noqa: BLE001 — pipeline self-healing
            self._failed(e, snapshot)

    def _failover_standalone(self) -> None:
        """Listener-mode upstream went away: detach the dead listener and
        rebuild as a standalone consumer from the live end, surfacing the
        regime change as one gap marker every tap sees."""
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None  # graftlint: owner=push-tap-poll
        qid, self.upstream_qid = self.upstream_qid, None
        try:
            self._build_standalone(from_beginning=False)
        except Exception as e:  # noqa: BLE001 — source dropped too: hand
            # recovery to the standalone retry ladder (mode must flip, or
            # every poll would re-enter this failover path ahead of the
            # backoff and flood the ring with gap markers)
            self.mode = "standalone"
            self._needs_rebuild = True
            self._failed(e, {})
            return
        self.restart_count += 1
        with self._lock:
            self.registry.heals += 1
        self._append_gap({
            "pipeline": self.id,
            "error": f"upstream query {qid} is gone; shared pipeline "
                     "failed over to a standalone consumer at the live end",
            "restarts": self.restart_count,
        })

    def _failed(self, e: Exception, snapshot: Dict) -> None:
        """classify → rewind → rebuild → backoff, pipeline-scoped: the
        identity pipeline is stateless, so rewinding the consumer to the
        pre-poll snapshot replays the whole failed batch (no rows lost);
        every tap observes exactly one in-ring gap marker per incident."""
        engine = self.engine
        engine._on_error(f"push-registry:{self.id}", e)
        if self.consumer is not None:
            self.consumer.positions.clear()
            self.consumer.positions.update(snapshot)
        self.restart_count += 1
        with self._lock:
            self.registry.heals += 1
        marker = {
            "pipeline": self.id,
            "error": f"{type(e).__name__}: {e}",
            "restarts": self.restart_count,
        }
        retry_max = int(
            engine.effective_property(cfg.QUERY_RETRY_MAX, 2 ** 31)
        )
        if self.restart_count > retry_max:
            self.terminal = True
            marker["terminal"] = True
        else:
            initial = float(engine.effective_property(
                cfg.QUERY_RETRY_BACKOFF_INITIAL_MS, 15000
            ))
            maximum = float(engine.effective_property(
                cfg.QUERY_RETRY_BACKOFF_MAX_MS, 900000
            ))
            self.retry_backoff_ms = min(
                (self.retry_backoff_ms * 2) or initial, maximum
            )
            self.retry_at_ms = self._now() + self.retry_backoff_ms
            try:
                self.executor = self._build_executor()
                self._needs_rebuild = False
            except Exception as e2:  # noqa: BLE001 — rebuild failed: the
                # next advance retries it after the backoff
                self._needs_rebuild = True
                engine._on_error(f"push-registry:{self.id}:rebuild", e2)
        self._append_gap(marker)

    @staticmethod
    def _now() -> float:
        return _now_ms()

    # ------------------------------------------------------------ refcount
    def attach(self, tap: PushTap) -> None:
        with self._lock:
            self.taps[tap.id] = tap
            self.idle_since_ms = None

    def detach(self, tap: PushTap) -> None:
        with self._lock:
            self.taps.pop(tap.id, None)
            if not self.taps:
                self.idle_since_ms = _now_ms()
        self.registry.sweep()

    def stop(self) -> None:
        """Teardown: unhook the listener, drop consumer + executor.  Under
        the registry lock so a concurrent listener-mode emit observes
        ``stopped`` and drops its row instead of appending to a dead
        ring."""
        with self._lock:
            self.stopped = True
            if self._unsubscribe is not None:
                self._unsubscribe()
                self._unsubscribe = None
            self.consumer = None
            self.executor = None
            self._emit_blocks.clear()  # release retained device arrays
            self._pending_block = None

    def healthy_row_count(self) -> int:
        with self._lock:
            return sum(1 for k, _ in self.ring if k == ROW)


class PushRegistry:
    """Engine-wide registry of shared push pipelines (the
    ScalablePushRegistry analog, generalized from one narrow attach case
    to every filter/projection push shape).  Owned by the engine via its
    ``get_push_registry`` seam; surfaced in /metrics as
    ``ksql_push_registry_pipelines`` / ``ksql_push_taps{registry}`` plus
    delivered/evicted/gap-marker counters."""

    def __init__(self, engine):
        self.engine = engine
        self._lock = threading.RLock()
        self._seq = itertools.count(1)
        self.pipelines: Dict[str, SharedPushPipeline] = {}
        # cumulative counters (survive pipeline teardown)
        self.delivered_rows = 0
        self.ring_evicted = 0
        self.gap_markers = 0
        self.heals = 0
        # fused-residual counters (ISSUE 12): kernel passes/rows, compile
        # epochs (one per capacity tier / row bucket), and pipelines that
        # degraded to host residuals after a kernel failure
        self.residual_kernel_evals = 0
        self.residual_kernel_rows = 0
        self.residual_compile_epochs = 0
        self.residual_degraded = 0

    # ------------------------------------------------------------ attaching
    def try_attach(self, session, planned, analysis) -> Optional[PushTap]:
        """Attach a new push session as a tap when its shape shares;
        returns the tap, or None (caller falls back to the legacy
        scalable attach, then to a dedicated session)."""
        engine = self.engine
        if not cfg._bool(
            engine.effective_property(cfg.PUSH_REGISTRY_ENABLE, True)
        ):
            return None
        if not cfg._bool(
            engine.config.get("ksql.query.push.v2.enabled", True)
        ):
            # the operator's master scalable-push opt-out covers the
            # registry tier too: sessions keep dedicated catchup consumers
            return None
        if len(getattr(analysis, "sources", ())) != 1:
            return None
        chain = residual_chain(planned.plan)
        if chain is None:
            return None
        source_step = chain[-1]
        source_name = getattr(source_step, "source_name", None) or (
            analysis.sources[0].source.name
        )
        with self._lock:
            self.sweep()
            pipe = self.pipelines.get(source_name)
            if pipe is None or pipe.stopped or pipe.terminal:
                if pipe is not None and not pipe.stopped:
                    pipe.stop()  # replaced terminal pipeline: release it
                pipe = SharedPushPipeline(self, source_name, source_name)
                self.pipelines[source_name] = pipe
            tap = PushTap(pipe, session, chain[:-1])
            pipe.attach(tap)
        return tap

    # ------------------------------------------------------------- reaping
    def sweep(self, now_ms: Optional[float] = None) -> None:
        """Reap pipelines idle past the linger window (refcounted
        teardown, deferred by ``ksql.push.registry.linger.ms`` so a
        reconnecting subscriber reuses the warm pipeline)."""
        now_ms = _now_ms() if now_ms is None else now_ms
        linger = float(self.engine.effective_property(
            cfg.PUSH_REGISTRY_LINGER_MS, 5000
        ))
        with self._lock:
            for key, pipe in list(self.pipelines.items()):
                idle = pipe.idle_since_ms
                if pipe.taps or idle is None:
                    continue
                if pipe.terminal or now_ms - idle >= linger:
                    pipe.stop()
                    self.pipelines.pop(key, None)

    def stop_all(self) -> None:
        """Engine shutdown: tear every pipeline down regardless of
        refcounts or linger."""
        with self._lock:
            for pipe in self.pipelines.values():
                pipe.stop()
            self.pipelines.clear()

    # ------------------------------------------------------ overload seams
    def pressure(self) -> float:
        """Laggiest-tap ring occupancy across every shared pipeline: the
        slowest tap's lag as a fraction of its pipeline's ring size (0.0
        idle, >= 1.0 means a tap is a full ring behind and about to take
        eviction gaps).  Raw ring FILL is deliberately not a signal — the
        ring is a sliding changelog that stays full in steady state; what
        overloads the push tier is consumers falling behind within it.
        The push resource the overload monitor samples each tick."""
        worst = 0.0
        with self._lock:
            for pipe in self.pipelines.values():
                size = max(int(pipe.ring_size), 1)
                for tap in pipe.taps.values():
                    worst = max(worst, tap.lag() / size)
        return worst

    def shed_laggards(self, bound: int) -> int:
        """Overload action: disconnect every tap lagging more than
        ``bound`` rows (0 = one full ring) behind its shared pipeline,
        with a TERMINAL gap marker naming overload — a shed subscriber
        sees an explicit close on the wire, never a silently stalled
        stream.  Returns the number of taps disconnected."""
        victims = []
        with self._lock:
            for pipe in self.pipelines.values():
                limit = int(bound) if bound > 0 else int(pipe.ring_size)
                for tap in list(pipe.taps.values()):
                    lag = tap.lag()
                    if lag > limit:
                        victims.append((pipe, tap, lag, limit))
        # markers + closes run OUTSIDE the registry lock: _enqueue_gap
        # takes the session lock and close() re-enters the registry lock
        # via detach — keep the acquisition order one lock at a time
        for pipe, tap, lag, limit in victims:
            marker = {
                "queryId": tap.session.id,
                "pipeline": pipe.id,
                "terminal": True,
                "overload": True,
                "lag": lag,
                "error": (
                    f"tap shed by the overload manager: {lag} rows behind "
                    f"the shared ring exceeds the overload lag bound "
                    f"({limit}); reconnect when pressure clears"
                ),
            }
            with self._lock:
                tap.gap_markers += 1
                self.gap_markers += 1
            tap.session._enqueue_gap(marker)
            tap.close()
        return len(victims)

    # ------------------------------------------------------------- metrics
    def stats(self) -> Dict[str, Any]:
        """The /metrics ``push-registry`` section (JSON; prometheus_text
        renders the same dict as the fan-out gauge/counter series)."""
        with self._lock:
            taps = {key: len(p.taps) for key, p in self.pipelines.items()}
            fused_taps = sum(
                p.kernel.fused_tap_count()
                for p in self.pipelines.values()
                if p.kernel is not None
            )
            detail = {
                key: {
                    "id": p.id,
                    "mode": p.mode,
                    "backend": p.backend,
                    "taps": len(p.taps),
                    "fusedTaps": (
                        p.kernel.fused_tap_count()
                        if p.kernel is not None else 0
                    ),
                    "residualDegraded": (
                        p.kernel.degraded
                        if p.kernel is not None else None
                    ),
                    "headSeq": p.base_seq + len(p.ring),
                    "restarts": p.restart_count,
                    "terminal": p.terminal,
                }
                for key, p in self.pipelines.items()
            }
            return {
                "pipelines": len(self.pipelines),
                "taps-total": sum(taps.values()),
                "taps": taps,
                "delivered-rows-total": self.delivered_rows,
                "ring-evicted-total": self.ring_evicted,
                "gap-markers-total": self.gap_markers,
                "heals-total": self.heals,
                "residual": {
                    "fused-taps": fused_taps,
                    "host-taps": sum(taps.values()) - fused_taps,
                    "kernel-evals-total": self.residual_kernel_evals,
                    "kernel-rows-total": self.residual_kernel_rows,
                    "compile-epochs-total": self.residual_compile_epochs,
                    "degraded-total": self.residual_degraded,
                },
                "pipeline-detail": detail,
            }
