"""SQL expression tree.

Analog of the reference's 45-node expression tree
(ksqldb-execution/.../execution/expression/tree/).  Nodes are immutable
dataclasses, JSON-serializable (plans embed expressions), and consumed by
three backends:

* the row interpreter (parity oracle / literal resolution) —
  ``execution/interpreter.py``;
* the columnar JAX compiler (device path) — ``compiler/jax_compiler.py``;
* the SQL formatter (EXPLAIN / DESCRIBE output) — ``format_expression``.
"""

import dataclasses
import enum
from typing import Any, Dict, List, Optional, Tuple

from ksql_tpu.common.types import SqlType

# --------------------------------------------------------------- registry

NODE_TYPES: Dict[str, type] = {}
ENUM_TYPES: Dict[str, type] = {}


def node(cls):
    """Register an AST/expression dataclass for JSON round-trip."""
    cls = dataclasses.dataclass(frozen=True)(cls)
    NODE_TYPES[cls.__name__] = cls
    return cls


def register_enum(cls):
    ENUM_TYPES[cls.__name__] = cls
    return cls


def encode(value: Any) -> Any:
    """Generic JSON encoding for node trees."""
    from ksql_tpu.common.schema import LogicalSchema

    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, bytes):
        return {"bytes": value.hex()}
    if isinstance(value, enum.Enum):
        return {"enum": f"{type(value).__name__}.{value.name}"}
    if isinstance(value, SqlType):
        return {"sqlType": value.to_json()}
    if isinstance(value, LogicalSchema):
        return {"schema": value.to_json()}
    if isinstance(value, (list, tuple)):
        return [encode(v) for v in value]
    if type(value).__name__ in NODE_TYPES:
        return {
            "node": type(value).__name__,
            "fields": {
                f.name: encode(getattr(value, f.name)) for f in dataclasses.fields(value)
            },
        }
    if isinstance(value, dict):
        return {"dict": [[encode(k), encode(v)] for k, v in value.items()]}
    raise TypeError(f"cannot encode {type(value).__name__}: {value!r}")


def decode(obj: Any) -> Any:
    from ksql_tpu.common.schema import LogicalSchema

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, list):
        return tuple(decode(v) for v in obj)
    if isinstance(obj, dict):
        if "bytes" in obj and len(obj) == 1:
            return bytes.fromhex(obj["bytes"])
        if "enum" in obj and len(obj) == 1:
            cls_name, member = obj["enum"].split(".")
            return ENUM_TYPES[cls_name][member]
        if "sqlType" in obj and len(obj) == 1:
            return SqlType.from_json(obj["sqlType"])
        if "schema" in obj and len(obj) == 1:
            return LogicalSchema.from_json(obj["schema"])
        if "dict" in obj and len(obj) == 1:
            return {decode(k): decode(v) for k, v in obj["dict"]}
        if "node" in obj:
            cls = NODE_TYPES[obj["node"]]
            kwargs = {k: decode(v) for k, v in obj["fields"].items()}
            return cls(**kwargs)
    raise TypeError(f"cannot decode {obj!r}")


class Expression:
    """Marker base class for expression nodes."""

    def __str__(self) -> str:
        return format_expression(self)


# ---------------------------------------------------------------- literals


@node
class NullLiteral(Expression):
    pass


@node
class BooleanLiteral(Expression):
    value: bool


@node
class IntegerLiteral(Expression):
    value: int  # INT32 range


@node
class LongLiteral(Expression):
    value: int


@node
class DoubleLiteral(Expression):
    value: float


@node
class DecimalLiteral(Expression):
    text: str  # exact textual form, e.g. "1.23"


@node
class StringLiteral(Expression):
    value: str


@node
class BytesLiteral(Expression):
    value: bytes


# --------------------------------------------------------------- references


@node
class ColumnRef(Expression):
    """Possibly source-qualified column reference (`s.col` or `col`)."""

    name: str
    source: Optional[str] = None


@node
class Dereference(Expression):
    """Struct field access: base->field."""

    base: Expression
    field: str


@node
class Subscript(Expression):
    """array[idx] (1-based per reference semantics) or map['key']."""

    base: Expression
    index: Expression


@node
class StructAll(Expression):
    """`base->*` struct-field expansion; only legal as a top-level select
    item, expanded by the analyzer into one column per struct field."""

    base: Expression


# -------------------------------------------------------------- operations


@register_enum
class ArithOp(enum.Enum):
    ADD = "+"
    SUBTRACT = "-"
    MULTIPLY = "*"
    DIVIDE = "/"
    MODULUS = "%"


@register_enum
class CompareOp(enum.Enum):
    EQ = "="
    NEQ = "<>"
    LT = "<"
    LTE = "<="
    GT = ">"
    GTE = ">="
    IS_DISTINCT_FROM = "IS DISTINCT FROM"
    IS_NOT_DISTINCT_FROM = "IS NOT DISTINCT FROM"


@register_enum
class LogicOp(enum.Enum):
    AND = "AND"
    OR = "OR"


@node
class ArithmeticBinary(Expression):
    op: ArithOp
    left: Expression
    right: Expression


@node
class ArithmeticUnary(Expression):
    op: ArithOp  # ADD or SUBTRACT
    operand: Expression


@node
class Comparison(Expression):
    op: CompareOp
    left: Expression
    right: Expression


@node
class LogicalBinary(Expression):
    op: LogicOp
    left: Expression
    right: Expression


@node
class Not(Expression):
    operand: Expression


@node
class IsNull(Expression):
    operand: Expression


@node
class IsNotNull(Expression):
    operand: Expression


@node
class Between(Expression):
    value: Expression
    lower: Expression
    upper: Expression
    negated: bool = False


@node
class InList(Expression):
    value: Expression
    items: Tuple[Expression, ...]
    negated: bool = False


@node
class Like(Expression):
    value: Expression
    pattern: Expression
    escape: Optional[str] = None
    negated: bool = False


@node
class Cast(Expression):
    operand: Expression
    target: SqlType


# ------------------------------------------------------------ conditionals


@node
class WhenClause(Expression):
    condition: Expression
    result: Expression


@node
class SearchedCase(Expression):
    """CASE WHEN c THEN r ... [ELSE d] END"""

    when_clauses: Tuple[WhenClause, ...]
    default: Optional[Expression] = None


@node
class SimpleCase(Expression):
    """CASE operand WHEN v THEN r ... [ELSE d] END"""

    operand: Expression
    when_clauses: Tuple[WhenClause, ...]
    default: Optional[Expression] = None


# ---------------------------------------------------------------- functions


@node
class FunctionCall(Expression):
    name: str
    args: Tuple[Expression, ...] = ()
    distinct: bool = False  # COUNT(DISTINCT x)


@node
class LambdaExpression(Expression):
    params: Tuple[str, ...]
    body: Expression


@node
class LambdaVariable(Expression):
    name: str


# --------------------------------------------------------- constructor exprs


@node
class CreateArray(Expression):
    items: Tuple[Expression, ...]


@node
class CreateMap(Expression):
    entries: Tuple[Tuple[Expression, Expression], ...]


@node
class CreateStruct(Expression):
    fields: Tuple[Tuple[str, Expression], ...]


# ------------------------------------------------------------ typed literals


@node
class TimeLiteral(Expression):
    text: str


@node
class DateLiteral(Expression):
    text: str


@node
class TimestampLiteral(Expression):
    text: str


@node
class IntervalUnit(Expression):
    """e.g. the `SECONDS` in SIZE 30 SECONDS (used inside window exprs)."""

    unit: str


# ---------------------------------------------------------------- traversal


def walk(expr: Any):
    """Pre-order traversal over all Expression nodes in a tree."""
    if isinstance(expr, Expression):
        yield expr
        for f in dataclasses.fields(expr):
            yield from walk(getattr(expr, f.name))
    elif isinstance(expr, (list, tuple)):
        for item in expr:
            yield from walk(item)


def rewrite(expr: Any, fn) -> Any:
    """Bottom-up rewrite: fn(node) -> replacement (or the node unchanged)."""
    if isinstance(expr, Expression):
        changed = {}
        for f in dataclasses.fields(expr):
            old = getattr(expr, f.name)
            new = rewrite(old, fn)
            if new is not old:
                changed[f.name] = new
        if changed:
            expr = dataclasses.replace(expr, **changed)
        return fn(expr)
    if isinstance(expr, tuple):
        return tuple(rewrite(item, fn) for item in expr)
    if isinstance(expr, list):
        return [rewrite(item, fn) for item in expr]
    return expr


def referenced_columns(expr: Any) -> List[str]:
    return [e.name for e in walk(expr) if isinstance(e, ColumnRef)]


# ---------------------------------------------------------------- formatting


def _fmt_str(s: str) -> str:
    return "'" + s.replace("'", "''") + "'"


def format_expression(e: Any) -> str:
    """Round-trippable SQL text (ExpressionFormatter analog)."""
    if isinstance(e, NullLiteral):
        return "null"
    if isinstance(e, BooleanLiteral):
        return "true" if e.value else "false"
    if isinstance(e, (IntegerLiteral, LongLiteral)):
        return str(e.value)
    if isinstance(e, DoubleLiteral):
        return repr(e.value)
    if isinstance(e, DecimalLiteral):
        return e.text
    if isinstance(e, StringLiteral):
        return _fmt_str(e.value)
    if isinstance(e, BytesLiteral):
        return f"X'{e.value.hex()}'"
    if isinstance(e, ColumnRef):
        return f"{e.source}.{e.name}" if e.source else e.name
    if isinstance(e, Dereference):
        return f"{format_expression(e.base)}->{e.field}"
    if isinstance(e, Subscript):
        return f"{format_expression(e.base)}[{format_expression(e.index)}]"
    if isinstance(e, StructAll):
        return f"{format_expression(e.base)}->*"
    if isinstance(e, ArithmeticBinary):
        return f"({format_expression(e.left)} {e.op.value} {format_expression(e.right)})"
    if isinstance(e, ArithmeticUnary):
        return f"{e.op.value}{format_expression(e.operand)}"
    if isinstance(e, Comparison):
        return f"({format_expression(e.left)} {e.op.value} {format_expression(e.right)})"
    if isinstance(e, LogicalBinary):
        return f"({format_expression(e.left)} {e.op.value} {format_expression(e.right)})"
    if isinstance(e, Not):
        return f"(NOT {format_expression(e.operand)})"
    if isinstance(e, IsNull):
        return f"({format_expression(e.operand)} IS NULL)"
    if isinstance(e, IsNotNull):
        return f"({format_expression(e.operand)} IS NOT NULL)"
    if isinstance(e, Between):
        neg = "NOT " if e.negated else ""
        return (
            f"({format_expression(e.value)} {neg}BETWEEN "
            f"{format_expression(e.lower)} AND {format_expression(e.upper)})"
        )
    if isinstance(e, InList):
        neg = "NOT " if e.negated else ""
        items = ", ".join(format_expression(i) for i in e.items)
        return f"({format_expression(e.value)} {neg}IN ({items}))"
    if isinstance(e, Like):
        neg = "NOT " if e.negated else ""
        esc = f" ESCAPE {_fmt_str(e.escape)}" if e.escape else ""
        return f"({format_expression(e.value)} {neg}LIKE {format_expression(e.pattern)}{esc})"
    if isinstance(e, Cast):
        return f"CAST({format_expression(e.operand)} AS {e.target})"
    if isinstance(e, SearchedCase):
        whens = " ".join(
            f"WHEN {format_expression(w.condition)} THEN {format_expression(w.result)}"
            for w in e.when_clauses
        )
        els = f" ELSE {format_expression(e.default)}" if e.default is not None else ""
        return f"(CASE {whens}{els} END)"
    if isinstance(e, SimpleCase):
        whens = " ".join(
            f"WHEN {format_expression(w.condition)} THEN {format_expression(w.result)}"
            for w in e.when_clauses
        )
        els = f" ELSE {format_expression(e.default)}" if e.default is not None else ""
        return f"(CASE {format_expression(e.operand)} {whens}{els} END)"
    if isinstance(e, FunctionCall):
        d = "DISTINCT " if e.distinct else ""
        return f"{e.name}({d}{', '.join(format_expression(a) for a in e.args)})"
    if isinstance(e, LambdaExpression):
        params = ", ".join(e.params)
        params = f"({params})" if len(e.params) != 1 else params
        return f"{params} => {format_expression(e.body)}"
    if isinstance(e, LambdaVariable):
        return e.name
    if isinstance(e, CreateArray):
        return f"ARRAY[{', '.join(format_expression(i) for i in e.items)}]"
    if isinstance(e, CreateMap):
        inner = ", ".join(
            f"{format_expression(k)}:={format_expression(v)}" for k, v in e.entries
        )
        return f"MAP({inner})"
    if isinstance(e, CreateStruct):
        inner = ", ".join(f"{n}:={format_expression(v)}" for n, v in e.fields)
        return f"STRUCT({inner})"
    if isinstance(e, TimeLiteral):
        return f"TIME {_fmt_str(e.text)}"
    if isinstance(e, DateLiteral):
        return f"DATE {_fmt_str(e.text)}"
    if isinstance(e, TimestampLiteral):
        return f"TIMESTAMP {_fmt_str(e.text)}"
    raise TypeError(f"cannot format {type(e).__name__}")
