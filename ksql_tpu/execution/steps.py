"""Physical-plan IR: the serializable ExecutionStep DAG.

Analog of ksqldb-execution's 29 step types (execution/plan/ExecutionStep.java:
30-59) — the versioned seam between *what to compute* and *how to run it*.
Plans serialize to JSON (golden-plan corpus, upgrade compatibility) and are
lowered by a backend visitor (runtime/lowering.py — the XlaPlanBuilder,
replacing the reference's KSPlanBuilder).

Every step carries its resolved output ``schema`` (the reference equivalently
resolves via StepSchemaResolver and embeds schemas in serialized plans) and a
``ctx`` step name used for state-store naming and query topology display.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ksql_tpu.common.schema import LogicalSchema
from ksql_tpu.execution.expressions import Expression, encode, decode, node
from ksql_tpu.parser.ast_nodes import JoinType, WindowExpression


class ExecutionStep:
    """Marker base.  Fields by convention: ``source`` (or left/right) child
    steps, ``schema`` output schema, ``ctx`` step name."""

    schema: LogicalSchema
    ctx: str

    def sources(self) -> Tuple["ExecutionStep", ...]:
        out = []
        for attr in ("source", "left", "right"):
            child = getattr(self, attr, None)
            if isinstance(child, ExecutionStep):
                out.append(child)
        return tuple(out)


@node
class FormatInfo:
    """Key/value serde formats for a step boundary (Formats.java analog).

    ``wrap_single_values`` mirrors SerdeFeature WRAP/UNWRAP_SINGLES on the
    value side (None = format default, i.e. wrapped); single key columns are
    always unwrapped for formats that support it (SerdeFeaturesFactory
    .buildKeyFeatures)."""

    key_format: str = "KAFKA"
    value_format: str = "JSON"
    wrap_single_values: Optional[bool] = None
    key_wrapped: bool = False  # inferred-record keys keep their envelope
    value_delimiter: Optional[str] = None  # DELIMITED custom delimiter
    key_delimiter: Optional[str] = None  # DELIMITED key delimiter


@node
class AggCall:
    """One aggregation: function + argument expressions over the pre-agg
    schema + trailing literal args (e.g. TOPK k)."""

    function: str
    args: Tuple[Expression, ...] = ()
    distinct: bool = False


# ------------------------------------------------------------------ sources


@node
class StreamSource(ExecutionStep):
    source_name: str
    topic: str
    schema: LogicalSchema
    formats: FormatInfo
    timestamp_column: Optional[str] = None
    timestamp_format: Optional[str] = None
    header_columns: Tuple = ()
    ctx: str = "Source"


@node
class WindowedStreamSource(ExecutionStep):
    source_name: str
    topic: str
    schema: LogicalSchema
    formats: FormatInfo
    window_type: str = "TUMBLING"
    window_size_ms: Optional[int] = None
    timestamp_column: Optional[str] = None
    timestamp_format: Optional[str] = None
    ctx: str = "Source"


@node
class TableSource(ExecutionStep):
    """Table source; materializes the changelog into a state store
    (SourceBuilderBase.java:45 forced materialization)."""

    source_name: str
    topic: str
    schema: LogicalSchema
    formats: FormatInfo
    timestamp_column: Optional[str] = None
    timestamp_format: Optional[str] = None
    state_store_name: str = ""
    header_columns: Tuple = ()
    ctx: str = "Source"


@node
class WindowedTableSource(ExecutionStep):
    source_name: str
    topic: str
    schema: LogicalSchema
    formats: FormatInfo
    window_type: str = "TUMBLING"
    window_size_ms: Optional[int] = None
    timestamp_column: Optional[str] = None
    timestamp_format: Optional[str] = None
    state_store_name: str = ""
    ctx: str = "Source"


# ----------------------------------------------------------- row transforms


@node
class StreamFilter(ExecutionStep):
    source: ExecutionStep
    predicate: Expression
    schema: LogicalSchema
    ctx: str = "Filter"


@node
class TableFilter(ExecutionStep):
    source: ExecutionStep
    predicate: Expression
    schema: LogicalSchema
    ctx: str = "Filter"


@node
class StreamSelect(ExecutionStep):
    """Projection: (alias, expression) pairs over the source schema.
    ``key_names`` optionally renames the (passed-through) key columns."""

    source: ExecutionStep
    selects: Tuple[Tuple[str, Expression], ...]
    schema: LogicalSchema
    key_names: Optional[Tuple[str, ...]] = None
    ctx: str = "Project"


@node
class TableSelect(ExecutionStep):
    source: ExecutionStep
    selects: Tuple[Tuple[str, Expression], ...]
    schema: LogicalSchema
    key_names: Optional[Tuple[str, ...]] = None
    ctx: str = "Project"


@node
class StreamSelectKey(ExecutionStep):
    """Re-key (PARTITION BY / join co-partitioning) — the shuffle boundary:
    lowered to an ICI all-to-all instead of a repartition topic."""

    source: ExecutionStep
    key_expressions: Tuple[Expression, ...]
    schema: LogicalSchema
    ctx: str = "PartitionBy"


@node
class TableSelectKey(ExecutionStep):
    source: ExecutionStep
    key_expressions: Tuple[Expression, ...]
    schema: LogicalSchema
    ctx: str = "PartitionBy"


@node
class StreamFlatMap(ExecutionStep):
    """UDTF explode (KudtfFlatMapper analog): selects may mix scalar
    expressions and table-function calls; each input row emits the cartesian
    alignment of its table-function outputs."""

    source: ExecutionStep
    table_functions: Tuple[Tuple[str, Expression], ...]  # (alias, FunctionCall)
    schema: LogicalSchema
    ctx: str = "FlatMap"


# ------------------------------------------------------------- aggregation


@node
class StreamGroupBy(ExecutionStep):
    source: ExecutionStep
    group_by_expressions: Tuple[Expression, ...]
    schema: LogicalSchema
    ctx: str = "GroupBy"


@node
class StreamGroupByKey(ExecutionStep):
    source: ExecutionStep
    schema: LogicalSchema
    ctx: str = "GroupByKey"


@node
class TableGroupBy(ExecutionStep):
    source: ExecutionStep
    group_by_expressions: Tuple[Expression, ...]
    schema: LogicalSchema
    ctx: str = "GroupBy"


@node
class StreamAggregate(ExecutionStep):
    """Unwindowed aggregate over a grouped stream.  ``non_agg_columns`` are
    the group-key columns carried into the value; ``aggregations`` produce
    KSQL_AGG_VARIABLE_i columns (KudafAggregator.java:56 semantics)."""

    source: ExecutionStep
    non_agg_columns: Tuple[str, ...]
    aggregations: Tuple[AggCall, ...]
    schema: LogicalSchema
    state_store_name: str = ""
    ctx: str = "Aggregate"


@node
class StreamWindowedAggregate(ExecutionStep):
    source: ExecutionStep
    non_agg_columns: Tuple[str, ...]
    aggregations: Tuple[AggCall, ...]
    window: WindowExpression
    schema: LogicalSchema
    state_store_name: str = ""
    ctx: str = "Aggregate"


@node
class TableAggregate(ExecutionStep):
    """Aggregate over a grouped *table*: handles retractions via undo
    (KudafUndoAggregator analog)."""

    source: ExecutionStep
    non_agg_columns: Tuple[str, ...]
    aggregations: Tuple[AggCall, ...]
    schema: LogicalSchema
    state_store_name: str = ""
    ctx: str = "Aggregate"


@node
class TableSuppress(ExecutionStep):
    """EMIT FINAL buffering (TableSuppressBuilder.java:39)."""

    source: ExecutionStep
    schema: LogicalSchema
    ctx: str = "Suppress"


# ------------------------------------------------------------------- joins


@node
class StreamStreamJoin(ExecutionStep):
    left: ExecutionStep
    right: ExecutionStep
    join_type: JoinType
    left_key: Expression
    right_key: Expression
    before_ms: int = 0
    after_ms: int = 0
    grace_ms: Optional[int] = None
    schema: LogicalSchema = None  # type: ignore[assignment]
    left_alias: str = "L"
    right_alias: str = "R"
    ctx: str = "Join"


@node
class StreamTableJoin(ExecutionStep):
    left: ExecutionStep
    right: ExecutionStep
    join_type: JoinType
    left_key: Expression
    right_key: Expression
    schema: LogicalSchema = None  # type: ignore[assignment]
    left_alias: str = "L"
    right_alias: str = "R"
    ctx: str = "Join"


@node
class TableTableJoin(ExecutionStep):
    left: ExecutionStep
    right: ExecutionStep
    join_type: JoinType
    left_key: Expression
    right_key: Expression
    schema: LogicalSchema = None  # type: ignore[assignment]
    left_alias: str = "L"
    right_alias: str = "R"
    ctx: str = "Join"


@node
class ForeignKeyTableTableJoin(ExecutionStep):
    left: ExecutionStep
    right: ExecutionStep
    join_type: JoinType
    foreign_key_expression: Expression
    schema: LogicalSchema = None  # type: ignore[assignment]
    left_alias: str = "L"
    right_alias: str = "R"
    ctx: str = "FkJoin"


# ------------------------------------------------------------------- sinks


@node
class StreamSink(ExecutionStep):
    source: ExecutionStep
    topic: str
    formats: FormatInfo
    schema: LogicalSchema
    timestamp_column: Optional[str] = None
    timestamp_format: Optional[str] = None
    # SR-schema-id sinks append schema columns absent from the query with
    # these write-defaults: ((name, default), ...)
    value_defaults: tuple = ()
    ctx: str = "Sink"


@node
class TableSink(ExecutionStep):
    source: ExecutionStep
    topic: str
    formats: FormatInfo
    schema: LogicalSchema
    timestamp_column: Optional[str] = None
    timestamp_format: Optional[str] = None
    # SR-schema-id sinks append schema columns absent from the query with
    # these write-defaults: ((name, default), ...)
    value_defaults: tuple = ()
    ctx: str = "Sink"


# ------------------------------------------------------------ plan wrappers


@node
class QueryPlan:
    """A complete persistent-query plan (QueryPlan.java analog)."""

    query_id: str
    sink_name: Optional[str]
    physical_plan: ExecutionStep
    source_names: Tuple[str, ...] = ()


PLAN_FORMAT_VERSION = 1


def plan_to_json(plan: QueryPlan) -> Dict[str, Any]:
    return {"version": PLAN_FORMAT_VERSION, "plan": encode(plan)}


def plan_from_json(obj: Dict[str, Any]) -> QueryPlan:
    version = obj.get("version", 1)
    if version > PLAN_FORMAT_VERSION:
        raise ValueError(f"plan format version {version} is newer than supported "
                         f"{PLAN_FORMAT_VERSION}")
    return decode(obj["plan"])


def walk_steps(step: ExecutionStep):
    """Post-order traversal of the step DAG."""
    for child in step.sources():
        yield from walk_steps(child)
    yield step


def format_plan(step: ExecutionStep, indent: int = 0) -> str:
    """Human-readable topology (EXPLAIN output)."""
    pad = " " * indent
    name = type(step).__name__
    extra = ""
    if hasattr(step, "source_name"):
        extra = f" [{step.source_name}]"
    elif hasattr(step, "predicate"):
        from ksql_tpu.execution.expressions import format_expression

        extra = f" [{format_expression(step.predicate)}]"
    elif hasattr(step, "topic"):
        extra = f" [{step.topic}]"
    lines = [f"{pad}> {name}{extra}"]
    for child in step.sources():
        lines.append(format_plan(child, indent + 2))
    return "\n".join(lines)
