"""Row-oriented expression typing + evaluation.

The analog of the reference's two expression backends — Janino codegen
(CodeGenRunner.java:62) and the interpreter (InterpretedExpressionFactory) —
collapsed into one: expressions are *compiled once* against a schema into a
closure tree (overload resolution, cast planning and null-handling decided at
compile time), then evaluated per row.

This is the parity oracle for the columnar XLA path and the execution engine
for paths where row-at-a-time is correct (INSERT VALUES literal resolution,
pull-query predicates, DDL defaults).

SQL semantics notes (matching the reference):
* three-valued logic for AND/OR/NOT/comparisons;
* Java integer division/modulus (truncate toward zero, remainder keeps
  dividend sign); arithmetic on NULL yields NULL; division by zero -> error
  -> NULL + processing-log;
* array subscripts are 1-based, negative indexes count from the end;
* evaluation errors yield NULL for the expression and invoke the
  processing-log callback (ProcessingLogger analog).
"""

from __future__ import annotations

import decimal as _decimal
import math
import re
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from ksql_tpu.common import types as T
from ksql_tpu.common.errors import FunctionException, SchemaException
from ksql_tpu.common.types import SqlBaseType, SqlType
from ksql_tpu.execution import expressions as ex
from ksql_tpu.functions.registry import FunctionRegistry
from ksql_tpu.functions.udfs import UNIT_ARG_FUNCTIONS

Row = Mapping[str, Any]
Evaluator = Callable[..., Any]  # (row, env) -> value


class TypeResolver:
    """Column name -> SqlType.  Qualified refs look up 'SOURCE.NAME' first."""

    def __init__(self, columns: Mapping[str, SqlType]):
        self.columns = dict(columns)

    def resolve(self, name: str, source: Optional[str]) -> SqlType:
        if source:
            q = f"{source}.{name}"
            if q in self.columns:
                return self.columns[q]
        if name in self.columns:
            return self.columns[name]
        raise SchemaException(f"unknown column {source + '.' if source else ''}{name}")

    def key_for(self, name: str, source: Optional[str]) -> str:
        if source:
            q = f"{source}.{name}"
            if q in self.columns:
                return q
        if name in self.columns:
            return name
        raise SchemaException(f"unknown column {source + '.' if source else ''}{name}")


class CompiledExpr:
    """A typed, compiled expression (CompiledExpression analog)."""

    def __init__(self, fn: Evaluator, sql_type: Optional[SqlType]):
        self._fn = fn
        self.sql_type = sql_type  # None = untyped NULL literal

    def __call__(self, row: Row, env: Optional[Dict[str, Any]] = None) -> Any:
        return self._fn(row, env)


class ExpressionCompiler:
    def __init__(
        self,
        resolver: TypeResolver,
        registry: FunctionRegistry,
        on_error: Optional[Callable[[str, Exception], None]] = None,
    ):
        self.resolver = resolver
        self.registry = registry
        self.on_error = on_error or (lambda expr, e: None)
        # >0 while compiling a lambda body: arithmetic on NULL then raises
        # (the Java codegen unboxes primitives — an NPE inside TRANSFORM/
        # FILTER/REDUCE nulls the whole result, unlike SQL null-propagation)
        self._lambda_depth = 0

    # ------------------------------------------------------------- public
    def compile(self, expr: ex.Expression) -> CompiledExpr:
        fn, t = self._compile(expr, {})
        guarded = self._guard(fn, expr)
        return CompiledExpr(guarded, t)

    def compile_raw(self, expr: ex.Expression) -> CompiledExpr:
        """Unguarded: evaluation errors propagate to the caller instead of
        becoming NULL-with-processing-log.  For contexts where an error
        must skip the whole row (UDTF parameter evaluation —
        KudtfFlatMapper wraps the entire flat-map in its try/catch)."""
        fn, t = self._compile(expr, {})
        return CompiledExpr(fn, t)

    def infer(self, expr: ex.Expression) -> Optional[SqlType]:
        _, t = self._compile(expr, {})
        return t

    def _guard(self, fn: Evaluator, expr: ex.Expression) -> Evaluator:
        text = None

        def guarded(row: Row, env=None):
            nonlocal text
            try:
                return fn(row, env)
            except Exception as e:  # evaluation error -> NULL + processing log
                if text is None:
                    text = ex.format_expression(expr)
                self.on_error(text, e)
                return None

        return guarded

    # ----------------------------------------------------------- dispatch
    def _compile(
        self, e: ex.Expression, lambda_types: Dict[str, SqlType]
    ) -> Tuple[Evaluator, Optional[SqlType]]:
        m = getattr(self, "_c_" + type(e).__name__, None)
        if m is None:
            raise SchemaException(f"cannot compile {type(e).__name__}")
        return m(e, lambda_types)

    # ------------------------------------------------------------ literals
    def _c_NullLiteral(self, e, lt):
        return (lambda r, v=None: None), None

    def _c_BooleanLiteral(self, e, lt):
        val = e.value
        return (lambda r, v=None: val), T.BOOLEAN

    def _c_IntegerLiteral(self, e, lt):
        val = e.value
        return (lambda r, v=None: val), T.INTEGER

    def _c_LongLiteral(self, e, lt):
        val = e.value
        return (lambda r, v=None: val), T.BIGINT

    def _c_DoubleLiteral(self, e, lt):
        val = e.value
        return (lambda r, v=None: val), T.DOUBLE

    def _c_DecimalLiteral(self, e, lt):
        text = e.text.lstrip("-")
        digits = text.replace(".", "").lstrip("0")
        precision = max(len(digits), 1)
        scale = len(text.split(".")[1]) if "." in text else 0
        val = _decimal.Decimal(e.text)
        return (lambda r, v=None: val), SqlType.decimal(max(precision, scale), scale)

    def _c_StringLiteral(self, e, lt):
        val = e.value
        return (lambda r, v=None: val), T.STRING

    def _c_BytesLiteral(self, e, lt):
        val = e.value
        return (lambda r, v=None: val), T.BYTES

    def _c_TimeLiteral(self, e, lt):
        val = _parse_time_text(e.text)
        return (lambda r, v=None: val), T.TIME

    def _c_DateLiteral(self, e, lt):
        import datetime as dt

        val = (dt.date.fromisoformat(e.text) - dt.date(1970, 1, 1)).days
        return (lambda r, v=None: val), T.DATE

    def _c_TimestampLiteral(self, e, lt):
        val = _parse_timestamp_text(e.text)
        return (lambda r, v=None: val), T.TIMESTAMP

    # ---------------------------------------------------------- references
    def _c_ColumnRef(self, e, lt):
        if e.source is None and e.name in lt:
            name = e.name
            t = lt[name]
            return (lambda r, env=None: (env or {}).get(name)), t
        key = self.resolver.key_for(e.name, e.source)
        t = self.resolver.resolve(e.name, e.source)
        return (lambda r, env=None: r.get(key)), t

    def _c_LambdaVariable(self, e, lt):
        name = e.name
        if name not in lt:
            raise SchemaException(f"unbound lambda variable {name}")
        t = lt[name]
        return (lambda r, env=None: (env or {}).get(name)), t

    def _c_Dereference(self, e, lt):
        base_fn, base_t = self._compile(e.base, lt)
        if base_t is None or base_t.base != SqlBaseType.STRUCT:
            raise SchemaException(f"cannot dereference non-struct: {e}")
        field_t = dict(base_t.fields or ()).get(e.field)
        if field_t is None:
            raise SchemaException(f"unknown struct field {e.field}")
        field = e.field

        def fn(r, env=None):
            base = base_fn(r, env)
            if base is None:
                return None
            return base.get(field)

        return fn, field_t

    def _c_Subscript(self, e, lt):
        base_fn, base_t = self._compile(e.base, lt)
        idx_fn, idx_t = self._compile(e.index, lt)
        if base_t is None:
            raise SchemaException("cannot subscript NULL")
        if base_t.base == SqlBaseType.ARRAY:

            def fn(r, env=None):
                base, idx = base_fn(r, env), idx_fn(r, env)
                if base is None or idx is None:
                    return None
                i = int(idx)
                n = len(base)
                if i > 0 and i <= n:
                    return base[i - 1]
                if i < 0 and -i <= n:
                    return base[i]
                return None

            return fn, base_t.element
        if base_t.base == SqlBaseType.MAP:

            def fn(r, env=None):
                base, idx = base_fn(r, env), idx_fn(r, env)
                if base is None or idx is None:
                    return None
                return base.get(idx)

            return fn, base_t.element
        raise SchemaException(f"cannot subscript {base_t}")

    # ---------------------------------------------------------- arithmetic
    def _c_ArithmeticUnary(self, e, lt):
        fn0, t0 = self._compile(e.operand, lt)
        if e.op == ex.ArithOp.ADD:
            return fn0, t0

        def fn(r, env=None):
            v = fn0(r, env)
            return None if v is None else -v

        return fn, t0

    def _c_ArithmeticBinary(self, e, lt):
        lf, ltype = self._compile(e.left, lt)
        rf, rtype = self._compile(e.right, lt)
        op = e.op
        # string concatenation via +
        if op == ex.ArithOp.ADD and (
            (ltype and ltype.base == SqlBaseType.STRING)
            or (rtype and rtype.base == SqlBaseType.STRING)
        ):
            def fn(r, env=None):
                a, b = lf(r, env), rf(r, env)
                if a is None or b is None:
                    return None
                return str(a) + str(b)

            return fn, T.STRING
        if ltype is None or rtype is None:
            out_t = ltype or rtype or T.BIGINT
        else:
            try:
                out_t = T.common_numeric_type(ltype, rtype)
            except TypeError:
                # ArithmeticInterpreter: "Error processing expression:
                # (true + 1.5). Unsupported arithmetic types. BOOLEAN DECIMAL"
                raise SchemaException(
                    "Error processing expression: "
                    f"{ex.format_expression(e)}. Unsupported arithmetic "
                    f"types. {ltype.base.value} {rtype.base.value}"
                ) from None
        int_out = out_t.base in (SqlBaseType.INTEGER, SqlBaseType.BIGINT)
        dec_out = out_t.base == SqlBaseType.DECIMAL
        dbl_out = out_t.base == SqlBaseType.DOUBLE
        py_op = _ARITH[op]
        strict_null = self._lambda_depth > 0

        def fn(r, env=None):
            a, b = lf(r, env), rf(r, env)
            if a is None or b is None:
                if strict_null:
                    raise FunctionException("null operand in lambda arithmetic")
                return None
            if dec_out:
                a, b = _to_decimal(a), _to_decimal(b)
            elif dbl_out:
                if isinstance(a, _decimal.Decimal):
                    a = float(a)
                if isinstance(b, _decimal.Decimal):
                    b = float(b)
            return py_op(a, b, int_out)

        return fn, out_t

    # ---------------------------------------------------------- comparison
    def _c_Comparison(self, e, lt):
        lf, ltype = self._compile(e.left, lt)
        rf, rtype = self._compile(e.right, lt)
        op = e.op
        if op == ex.CompareOp.IS_DISTINCT_FROM:
            def fn(r, env=None):
                a, b = lf(r, env), rf(r, env)
                return not _sql_equal(a, b)
            return fn, T.BOOLEAN
        if op == ex.CompareOp.IS_NOT_DISTINCT_FROM:
            def fn(r, env=None):
                a, b = lf(r, env), rf(r, env)
                return _sql_equal(a, b)
            return fn, T.BOOLEAN
        if isinstance(e.left, ex.NullLiteral) or isinstance(e.right, ex.NullLiteral):
            # only IS [NOT] DISTINCT FROM compares against literal NULL
            raise SchemaException(
                "Comparison with NULL not supported: "
                f"{ex.format_expression(e.left)} {e.op.name} "
                f"{ex.format_expression(e.right)}"
            )
        # magic timestamp conversion: ROWTIME/WINDOWSTART/WINDOWEND compared
        # against timestamp-like strings (partial forms allowed)
        l_magic = (
            _is_ts_pseudo_ref(e.left)
            and ltype is not None
            and ltype.base == SqlBaseType.BIGINT
            and rtype is not None
            and rtype.base == SqlBaseType.STRING
        )
        r_magic = (
            _is_ts_pseudo_ref(e.right)
            and rtype is not None
            and rtype.base == SqlBaseType.BIGINT
            and ltype is not None
            and ltype.base == SqlBaseType.STRING
        )
        # compile-time comparability check (reference ComparisonUtil)
        if ltype is not None and rtype is not None and not (l_magic or r_magic):
            lb, rb = ltype.base, rtype.base
            temporal_bases = {SqlBaseType.TIMESTAMP, SqlBaseType.DATE, SqlBaseType.TIME}
            comparable = (
                lb == rb
                or (ltype.is_numeric() and rtype.is_numeric())
                # temporal types compare against STRING (coerced); DATE and
                # TIMESTAMP compare against each other (date -> midnight ts)
                or (lb in temporal_bases and rb == SqlBaseType.STRING)
                or (rb in temporal_bases and lb == SqlBaseType.STRING)
                or {lb, rb} == {SqlBaseType.DATE, SqlBaseType.TIMESTAMP}
            )
            # structured types + booleans support equality only
            # (SqlToJavaVisitor.visitArray/Map/StructComparisonExpression)
            eq_only = {SqlBaseType.ARRAY, SqlBaseType.MAP, SqlBaseType.STRUCT,
                       SqlBaseType.BOOLEAN}
            if lb == rb and lb in eq_only and op not in (
                ex.CompareOp.EQ, ex.CompareOp.NEQ
            ):
                comparable = False
            if not comparable:
                # message mirrors ComparisonInterpreter/CompareToTerm: full
                # SQL type strings + Java ComparisonExpression.Type names
                java_op = {
                    "EQ": "EQUAL", "NEQ": "NOT_EQUAL",
                    "LT": "LESS_THAN", "LTE": "LESS_THAN_OR_EQUAL",
                    "GT": "GREATER_THAN", "GTE": "GREATER_THAN_OR_EQUAL",
                }.get(op.name, op.name)
                ldisp = getattr(e.left, "_display", None) or ex.format_expression(e.left)
                rdisp = getattr(e.right, "_display", None) or ex.format_expression(e.right)
                raise SchemaException(
                    f"Cannot compare {ldisp} ({ltype}) "
                    f"to {rdisp} ({rtype}) with {java_op}."
                )
        cmp = _COMPARE[op]
        # temporal-vs-string comparisons coerce the string side
        temporal = {SqlBaseType.TIMESTAMP: _parse_timestamp_text,
                    SqlBaseType.TIME: _parse_time_text}
        l_coerce = r_coerce = None
        if l_magic:
            r_coerce = _parse_timestamp_text
        elif r_magic:
            l_coerce = _parse_timestamp_text
        elif ltype is not None and rtype is not None:
            if ltype.base in temporal and rtype.base == SqlBaseType.STRING:
                r_coerce = temporal[ltype.base]
            elif rtype.base in temporal and ltype.base == SqlBaseType.STRING:
                l_coerce = temporal[rtype.base]
            elif ltype.base == SqlBaseType.DATE and rtype.base == SqlBaseType.STRING:
                r_coerce = _parse_date_text
            elif rtype.base == SqlBaseType.DATE and ltype.base == SqlBaseType.STRING:
                l_coerce = _parse_date_text
            elif (
                ltype.base == SqlBaseType.DATE
                and rtype.base == SqlBaseType.TIMESTAMP
            ):
                l_coerce = _date_to_ts
            elif (
                rtype.base == SqlBaseType.DATE
                and ltype.base == SqlBaseType.TIMESTAMP
            ):
                r_coerce = _date_to_ts
            elif ltype.base == SqlBaseType.DECIMAL and rtype.base == SqlBaseType.DOUBLE:
                l_coerce = float
            elif rtype.base == SqlBaseType.DECIMAL and ltype.base == SqlBaseType.DOUBLE:
                r_coerce = float

        def fn(r, env=None):
            a, b = lf(r, env), rf(r, env)
            # NULL operand -> false, not NULL (SqlToJavaVisitor.nullCheckPrefix:621)
            if a is None or b is None:
                return False
            if l_coerce is not None:
                a = l_coerce(a)
            if r_coerce is not None:
                b = r_coerce(b)
            return cmp(a, b)

        return fn, T.BOOLEAN

    def _c_LogicalBinary(self, e, lt):
        lf, _ = self._compile(e.left, lt)
        rf, _ = self._compile(e.right, lt)
        if e.op == ex.LogicOp.AND:
            def fn(r, env=None):
                a = lf(r, env)
                if a is False:
                    return False
                b = rf(r, env)
                if b is False:
                    return False
                if a is None or b is None:
                    return None
                return True
            return fn, T.BOOLEAN

        def fn(r, env=None):
            a = lf(r, env)
            if a is True:
                return True
            b = rf(r, env)
            if b is True:
                return True
            if a is None or b is None:
                return None
            return False

        return fn, T.BOOLEAN

    def _c_Not(self, e, lt):
        f, _ = self._compile(e.operand, lt)

        def fn(r, env=None):
            v = f(r, env)
            return None if v is None else (not v)

        return fn, T.BOOLEAN

    def _c_IsNull(self, e, lt):
        f, _ = self._compile(e.operand, lt)
        return (lambda r, env=None: f(r, env) is None), T.BOOLEAN

    def _c_IsNotNull(self, e, lt):
        f, _ = self._compile(e.operand, lt)
        return (lambda r, env=None: f(r, env) is not None), T.BOOLEAN

    def _c_Between(self, e, lt):
        vf, vt = self._compile(e.value, lt)
        lo, lot = self._compile(e.lower, lt)
        hi, hit = self._compile(e.upper, lt)
        negated = e.negated
        if _is_ts_pseudo_ref(e.value) and vt is not None and vt.base == SqlBaseType.BIGINT:
            lo_c = _parse_timestamp_text if lot is not None and lot.base == SqlBaseType.STRING else None
            hi_c = _parse_timestamp_text if hit is not None and hit.base == SqlBaseType.STRING else None
        else:
            lo_c = _between_coercer(vt, lot)
            hi_c = _between_coercer(vt, hit)

        def fn(r, env=None):
            v, a, b = vf(r, env), lo(r, env), hi(r, env)
            if v is None or a is None or b is None:
                return None
            if lo_c is not None:
                a = lo_c(a)
            if hi_c is not None:
                b = hi_c(b)
            if isinstance(v, _decimal.Decimal) and (
                isinstance(a, float) or isinstance(b, float)
            ):
                v = float(v)
            res = a <= v <= b
            return (not res) if negated else res

        return fn, T.BOOLEAN

    def _c_InList(self, e, lt):
        vf, vt = self._compile(e.value, lt)
        compiled_items = [self._compile(i, lt) for i in e.items]
        item_coercers = [None] * len(compiled_items)
        if vt is not None:
            for idx, (item_expr, (_, it)) in enumerate(zip(e.items, compiled_items)):
                if it is None:
                    continue
                item_coercers[idx] = self._in_item_coercer(item_expr, it, vt)

        def _coerced(f, c):
            def g(r, env=None):
                v = f(r, env)
                return None if v is None else c(v)

            return g

        items = [
            (f if c is None else _coerced(f, c))
            for (f, _), c in zip(compiled_items, item_coercers)
        ]
        negated = e.negated

        def fn(r, env=None):
            v = vf(r, env)
            if v is None:
                return None
            saw_null = False
            for itf in items:
                item = itf(r, env)
                if item is None:
                    saw_null = True
                elif item is _IN_NO_MATCH:
                    continue
                elif _in_equal(v, item):
                    return not negated
            if saw_null:
                return None
            return negated

        return fn, T.BOOLEAN

    def _in_item_coercer(self, item_expr, it, vt):
        """Validate an IN-list item against the LHS type and return an
        optional runtime coercer.  Literal strings coerce leniently
        (reference DefaultSqlValueCoercer): booleans accept true/yes/false/no
        prefixes, numerics parse decimal text, temporals parse ISO text;
        incompatible items raise at planning time."""
        temporal_coerce = {
            SqlBaseType.TIMESTAMP: _parse_timestamp_text,
            SqlBaseType.DATE: _parse_date_text,
            SqlBaseType.TIME: _parse_time_text,
        }

        is_str_lit = isinstance(item_expr, ex.StringLiteral)

        def invalid():
            # literal text that doesn't parse as the LHS type
            # (DefaultSqlValueCoercer: "Invalid Predicate: invalid input
            # syntax for type BIGINT: \"10 - not a number\"").  Only
            # string-literal items reach here; non-literals raise
            # mismatch() instead.
            return SchemaException(
                "Invalid Predicate: invalid input syntax for type "
                f'{vt.base.value}: "{item_expr.value}"'
            )

        def mismatch():
            # structurally incomparable operand types ("operator does not
            # exist: INTEGER = BOOLEAN (true)" — note the reference's
            # spelling "comparision" lives in the join variant, not here)
            return SchemaException(
                "Invalid Predicate: operator does not exist: "
                f"{vt} = {it} ({ex.format_expression(item_expr)})"
            )
        if vt.base in temporal_coerce and it.base == SqlBaseType.STRING:
            return temporal_coerce[vt.base]
        if vt.base == SqlBaseType.BOOLEAN and it.base == SqlBaseType.STRING:
            if not is_str_lit:
                raise mismatch()  # only literals coerce across the divide
            if _parse_bool_lenient(item_expr.value) is None:
                raise invalid()
            return _parse_bool_lenient
        if vt.is_numeric() and it.base == SqlBaseType.STRING:
            if not is_str_lit:
                raise mismatch()
            try:
                float(item_expr.value)
            except ValueError:
                raise invalid() from None
            if vt.base in (SqlBaseType.INTEGER, SqlBaseType.BIGINT):
                # exact comparison for 64-bit integers (no float rounding)
                import decimal as _dec

                return lambda s: _dec.Decimal(s)
            return lambda s: float(s)
        if vt.base == SqlBaseType.STRING and it.base == SqlBaseType.BOOLEAN:
            return lambda b: "true" if b else "false"
        if vt.base == SqlBaseType.STRING and it.is_numeric():
            if isinstance(item_expr, ex.DecimalLiteral):
                # decimal literals keep their exact textual form ("10.30")
                return lambda _v, s=item_expr.text: s
            if ex.referenced_columns(item_expr):
                # only literals coerce across the STRING/number divide
                raise mismatch()
            return _number_to_string
        if vt.base == SqlBaseType.ARRAY and it.base == SqlBaseType.ARRAY:
            if isinstance(item_expr, ex.CreateArray) and vt.element is not None:
                el_coercers = []
                for el in item_expr.items:
                    et = self.infer(el)
                    if et is None:
                        el_coercers.append(None)
                        continue
                    try:
                        el_coercers.append(self._in_item_coercer(el, et, vt.element))
                    except SchemaException:
                        raise mismatch() from None
                if any(c is not None for c in el_coercers):
                    return lambda lst: [
                        (c(x) if c is not None and x is not None else x)
                        for c, x in zip(el_coercers, lst)
                    ]
            return None
        if vt.base == SqlBaseType.MAP and it.base == SqlBaseType.MAP:
            if isinstance(item_expr, ex.CreateMap) and vt.element is not None:
                v_coercers = {}
                for k, mv in item_expr.entries:
                    mt = self.infer(mv)
                    if mt is None:
                        continue
                    try:
                        c = self._in_item_coercer(mv, mt, vt.element)
                    except SchemaException:
                        raise mismatch() from None
                    if c is not None and isinstance(k, ex.StringLiteral):
                        v_coercers[k.value] = c
                if v_coercers:
                    return lambda m: {
                        k: (
                            v_coercers[k](v)
                            if k in v_coercers and v is not None
                            else v
                        )
                        for k, v in m.items()
                    }
            return None
        if vt.base == SqlBaseType.STRUCT and it.base == SqlBaseType.STRUCT:
            if isinstance(item_expr, ex.CreateStruct):
                # struct literals coerce to the LHS schema: missing fields
                # become null, a field outside the schema makes the item
                # uncoercible (it can never match — reference
                # DefaultSqlValueCoercer struct rules)
                fts = dict(vt.fields or ())
                lit_names = {n for n, _ in item_expr.fields}
                if lit_names - set(fts):
                    return lambda d: _IN_NO_MATCH
                f_coercers = {}
                for fname, fv in item_expr.fields:
                    ft = fts.get(fname)
                    st_ = self.infer(fv)
                    if ft is None or st_ is None:
                        continue
                    try:
                        c = self._in_item_coercer(fv, st_, ft)
                    except SchemaException:
                        raise mismatch() from None
                    if c is not None:
                        f_coercers[fname] = c

                field_order = [n for n, _ in (vt.fields or ())]

                def reshape(d, _order=field_order, _co=f_coercers):
                    out = {}
                    for n in _order:
                        v = d.get(n)
                        c = _co.get(n)
                        out[n] = c(v) if c is not None and v is not None else v
                    return out

                return reshape
            return None
        if it.base == vt.base or (vt.is_numeric() and it.is_numeric()):
            return None
        raise mismatch()

    def _c_Like(self, e, lt):
        vf, _ = self._compile(e.value, lt)
        pf, _ = self._compile(e.pattern, lt)
        escape = e.escape
        negated = e.negated
        cache: Dict[str, re.Pattern] = {}

        def fn(r, env=None):
            v, p = vf(r, env), pf(r, env)
            if v is None or p is None:
                return None
            rx = cache.get(p)
            if rx is None:
                rx = _like_to_regex(p, escape)
                cache[p] = rx
            res = rx.fullmatch(v) is not None
            return (not res) if negated else res

        return fn, T.BOOLEAN

    # --------------------------------------------------------- conditionals
    def _c_SearchedCase(self, e, lt):
        whens = [
            (self._compile(w.condition, lt)[0], self._compile(w.result, lt))
            for w in e.when_clauses
        ]
        default = self._compile(e.default, lt) if e.default is not None else None
        out_t = next((t for _, (_, t) in whens if t is not None), None)
        if out_t is None and default is not None:
            out_t = default[1]
        if out_t is None:
            raise SchemaException(
                "Invalid Case expression. All case branches have NULL type"
            )
        when_fns = [(c, rf) for c, (rf, _) in whens]
        dfn = default[0] if default else (lambda r, env=None: None)

        def fn(r, env=None):
            for cond, res in when_fns:
                if cond(r, env) is True:
                    return res(r, env)
            return dfn(r, env)

        return fn, out_t

    def _c_SimpleCase(self, e, lt):
        op_f, _ = self._compile(e.operand, lt)
        whens = [
            (self._compile(w.condition, lt)[0], self._compile(w.result, lt))
            for w in e.when_clauses
        ]
        default = self._compile(e.default, lt) if e.default is not None else None
        out_t = next((t for _, (_, t) in whens if t is not None), None)
        if out_t is None and default is not None:
            out_t = default[1]
        when_fns = [(c, rf) for c, (rf, _) in whens]
        dfn = default[0] if default else (lambda r, env=None: None)

        def fn(r, env=None):
            v = op_f(r, env)
            if v is not None:
                for cond, res in when_fns:
                    c = cond(r, env)
                    if c is not None and _sql_equal(v, c):
                        return res(r, env)
            return dfn(r, env)

        return fn, out_t

    # ---------------------------------------------------------------- cast
    def _c_Cast(self, e, lt):
        f, src_t = self._compile(e.operand, lt)
        target = e.target
        caster = make_caster(src_t, target)

        def fn(r, env=None):
            v = f(r, env)
            if v is None:
                return None
            return caster(v)

        return fn, target

    # ----------------------------------------------------------- functions
    def _c_FunctionCall(self, e, lt):
        name = e.name.upper()
        # interval-unit first arg (TIMESTAMPADD(MINUTES, ...)) parses as a
        # column ref; rewrite to a string literal
        args = list(e.args)
        if name in UNIT_ARG_FUNCTIONS:
            from ksql_tpu.functions.udfs import _UNIT_MS

            pos = UNIT_ARG_FUNCTIONS[name]
            if (
                pos < len(args)
                and isinstance(args[pos], ex.ColumnRef)
                and args[pos].name.upper() in _UNIT_MS
                and args[pos].source is None
            ):
                # a bare interval-unit keyword, not a real column reference
                args[pos] = ex.StringLiteral(value=args[pos].name)
        if self.registry.is_aggregate(name):
            raise SchemaException(
                f"aggregate function {name} not allowed here (non-aggregate context)"
            )
        sf = self.registry.scalar(name)
        compiled: List[Tuple[Evaluator, Optional[SqlType]]] = []
        arg_types: List[SqlType] = []
        lambda_args: Dict[int, ex.LambdaExpression] = {}
        for idx, a in enumerate(args):
            if isinstance(a, ex.LambdaExpression):
                lambda_args[idx] = a
                compiled.append((None, None))  # type: ignore[arg-type]
                arg_types.append(T.STRING)  # placeholder; matcher is t_lambda
            else:
                fn_t = self._compile(a, lt)
                compiled.append(fn_t)
                arg_types.append(fn_t[1])  # None = untyped NULL (matches any)
        variant = sf.resolve(arg_types)
        # compile lambda args now that the collection types are known
        lambda_ret_types: Dict[int, Optional[SqlType]] = {}
        for idx, lam in lambda_args.items():
            param_types = _lambda_param_types(name, idx, arg_types, compiled, lam)
            body_lt = dict(lt)
            body_lt.update({p: t for p, t in zip(lam.params, param_types)})
            self._lambda_depth += 1
            try:
                body_fn, body_t = self._compile(lam.body, body_lt)
            finally:
                self._lambda_depth -= 1
            lambda_ret_types[idx] = body_t
            params = lam.params

            def make_callable(body_fn=body_fn, params=params):
                def lam_fn(r, env):
                    def call(*vals):
                        new_env = dict(env or {})
                        new_env.update(dict(zip(params, vals)))
                        return body_fn(r, new_env)

                    return call

                return lam_fn

            compiled[idx] = (make_callable(), None)
        # return type: lambda-aware
        ret_types_for_resolution = [
            t if t is not None else T.STRING for t in arg_types
        ]
        for idx, bt in lambda_ret_types.items():
            ret_types_for_resolution[idx] = bt if bt is not None else T.STRING
        out_t = variant.return_type(
            list(arg_types) if variant.typed_factory else ret_types_for_resolution
        )
        null_tolerant = variant.null_tolerant
        arg_fns = [c[0] for c in compiled]
        lam_idx = set(lambda_args)
        impl = variant.fn
        if variant.typed_factory:
            # factories see raw arg types (None = untyped NULL literal)
            impl = impl(list(arg_types))

        def fn(r, env=None):
            vals = []
            for i, af in enumerate(arg_fns):
                v = af(r, env)
                if i not in lam_idx and v is None and not null_tolerant:
                    return None
                vals.append(v)
            return impl(*vals)

        return fn, out_t

    def _c_LambdaExpression(self, e, lt):
        raise SchemaException("lambda only allowed as a function argument")

    # ---------------------------------------------------------- constructors
    def _c_CreateArray(self, e, lt):
        if not e.items:
            raise SchemaException(
                "Array constructor cannot be empty. Please supply at least one "
                "element (see https://github.com/confluentinc/ksql/issues/4239)."
            )
        items = [self._compile(i, lt) for i in e.items]
        el_t = _common_constructor_type(
            [t for _, t in items], list(e.items), "array"
        )
        fns = [
            _guard_element(_constructor_coercer(f, t, el_t, it))
            for (f, t), it in zip(items, e.items)
        ]

        def fn(r, env=None):
            return [f(r, env) for f in fns]

        return fn, SqlType.array(el_t)

    def _c_CreateMap(self, e, lt):
        if not e.entries:
            raise SchemaException(
                "Map constructor cannot be empty. Please supply at least one "
                "key value pair (see https://github.com/confluentinc/ksql/issues/4239)."
            )
        entries = [
            (self._compile(k, lt), self._compile(v, lt)) for k, v in e.entries
        ]
        if all(kt is None for (_, kt), _v in entries):
            raise SchemaException(
                "Cannot construct a map with all NULL keys (see "
                "https://github.com/confluentinc/ksql/issues/4239)."
            )
        v_t = _common_constructor_type(
            [vt for _k, (_, vt) in entries], [v for _k, v in e.entries], "map"
        )
        pairs = [
            (kf, _guard_element(_constructor_coercer(vf, vt, v_t, ve)))
            for ((kf, _kt), (vf, vt)), (_ke, ve) in zip(entries, e.entries)
        ]
        # literal keys coerce to STRING (CoercionUtil); only a non-literal
        # key of a non-string type makes the map non-string-keyed
        k_t = T.STRING
        for ((_, kt), _v), (ke, _ve) in zip(entries, e.entries):
            if (
                kt is not None
                and kt.base != SqlBaseType.STRING
                and ex.referenced_columns(ke)
            ):
                k_t = kt
                break
        if k_t.base == SqlBaseType.STRING:
            def fn(r, env=None):
                return {_map_key_str(kf(r, env)): vf(r, env) for kf, vf in pairs}
        else:
            # non-string keys keep their type; formats that can't serialize
            # them reject at sink-schema validation
            def fn(r, env=None):
                return {kf(r, env): vf(r, env) for kf, vf in pairs}

        return fn, SqlType.map(k_t, v_t)

    def _c_CreateStruct(self, e, lt):
        names = [n for n, _ in e.fields]
        if len(set(names)) != len(names):  # exact: quoted ids keep case
            raise SchemaException("Duplicate field names found in STRUCT")
        fields = [(n, self._compile(v, lt)) for n, v in e.fields]
        t = SqlType.struct([(n, ft if ft is not None else T.STRING) for n, (_, ft) in fields])
        fns = [(n, _guard_element(f)) for n, (f, _) in fields]

        def fn(r, env=None):
            return {n: f(r, env) for n, f in fns}

        return fn, t


# ------------------------------------------------------------- SQL helpers


def _guard_element(f):
    """Constructor-element guard: an ARRAY[]/MAP()/STRUCT() element whose
    expression errors becomes NULL instead of nulling the whole value
    (reference CreateArrayExpression element evaluation logs-and-nulls)."""

    def g(r, env=None):
        try:
            return f(r, env)
        except Exception:
            return None

    return g


def _map_key_str(k):
    if k is None:
        return None
    if isinstance(k, bool):
        return "true" if k else "false"
    return k if isinstance(k, str) else str(k)


def _common_constructor_type(types, exprs, what: str):
    """Common element/value type for ARRAY[]/MAP() constructors (reference
    CoercionUtil): string literals coerce to the non-string type when one
    exists; all-null constructors are rejected."""
    non_null = [t for t in types if t is not None]
    if not non_null:
        noun = "an array with all NULL elements" if what == "array" else (
            "a map with all NULL values"
        )
        raise SchemaException(
            f"Cannot construct {noun} (see "
            "https://github.com/confluentinc/ksql/issues/4239). As a "
            "workaround, you may cast a NULL value to the desired type."
        )
    non_str = [t for t in non_null if t.base != SqlBaseType.STRING]
    if not non_str:
        return non_null[0]
    target = non_str[0]
    for t in non_str[1:]:
        if t == target:
            continue
        if t.is_numeric() and target.is_numeric():
            target = T.common_numeric_type(target, t)
        elif t.base != target.base:
            raise SchemaException(
                f"invalid input syntax for type {target.base.value}: "
                "mismatching types in constructor"
            )
    # string literals must be coercible to the target
    for t, ex_ in zip(types, exprs):
        if t is not None and t.base == SqlBaseType.STRING and target.base != SqlBaseType.STRING:
            if not isinstance(ex_, ex.StringLiteral):
                raise SchemaException(
                    f"invalid input syntax for type {target.base.value}: "
                    f"{ex.format_expression(ex_)}"
                )
            if _coerce_literal_text(ex_.value, target) is None:
                raise SchemaException(
                    f"invalid input syntax for type {target.base.value}: "
                    f'"{ex_.value}"'
                )
    return target


def _coerce_literal_text(sv: str, target):
    """Parse literal text into the target type's host value, or None."""
    b = target.base
    try:
        if b == SqlBaseType.BOOLEAN:
            return _parse_bool_lenient(sv)
        if b in (SqlBaseType.INTEGER, SqlBaseType.BIGINT):
            d = _decimal.Decimal(sv)
            return int(d) if d == d.to_integral_value() else None
        if b == SqlBaseType.DOUBLE:
            return float(sv)
        if b == SqlBaseType.DECIMAL:
            return _decimal.Decimal(sv)
        if b == SqlBaseType.TIMESTAMP:
            return _parse_timestamp_text(sv)
        if b == SqlBaseType.DATE:
            return _parse_date_text(sv)
        if b == SqlBaseType.TIME:
            return _parse_time_text(sv)
    except Exception:
        return None
    return None


def _constructor_coercer(f, t, target, expr):
    """Wrap an element evaluator so string literals land in the constructor's
    common type."""
    if (
        t is not None
        and t.base == SqlBaseType.STRING
        and target.base != SqlBaseType.STRING
        and isinstance(expr, ex.StringLiteral)
    ):
        const = _coerce_literal_text(expr.value, target)
        return lambda r, env=None: const
    if target.base == SqlBaseType.STRING and t is not None and t.base != SqlBaseType.STRING:
        def g(r, env=None):
            v = f(r, env)
            return None if v is None else _number_to_string(v)
        return g
    return f


def _java_int_div(a, b, int_out: bool):
    if int_out:
        if b == 0:
            raise ZeroDivisionError("division by zero")
        q = abs(a) // abs(b)
        return q if (a >= 0) == (b >= 0) else -q
    if isinstance(a, _decimal.Decimal) or isinstance(b, _decimal.Decimal):
        # BigDecimal division by zero is an ArithmeticException (-> null+log)
        return _to_decimal(a) / _to_decimal(b)
    # Java double division by zero yields Infinity/NaN, not an error
    if b == 0:
        a = float(a)
        if a == 0 or a != a:  # 0/0 and NaN/0 are NaN
            return float("nan")
        return float("inf") if a > 0 else float("-inf")
    return a / b


def _java_mod(a, b, int_out: bool):
    if b == 0:
        if int_out:
            raise ZeroDivisionError("modulus by zero")
        if isinstance(a, _decimal.Decimal) or isinstance(b, _decimal.Decimal):
            # BigDecimal.remainder(ZERO) throws -> null (not NaN)
            raise ZeroDivisionError("decimal modulus by zero")
        return float("nan")
    if int_out:
        r = abs(a) % abs(b)
        return r if a >= 0 else -r
    if isinstance(a, _decimal.Decimal) and isinstance(b, _decimal.Decimal):
        r = abs(a) % abs(b)
        return r if a >= 0 else -r
    return math.fmod(a, b)


_ARITH = {
    ex.ArithOp.ADD: lambda a, b, i: a + b,
    ex.ArithOp.SUBTRACT: lambda a, b, i: a - b,
    ex.ArithOp.MULTIPLY: lambda a, b, i: a * b,
    ex.ArithOp.DIVIDE: _java_int_div,
    ex.ArithOp.MODULUS: _java_mod,
}

_COMPARE = {
    ex.CompareOp.EQ: lambda a, b: _sql_equal(a, b),
    ex.CompareOp.NEQ: lambda a, b: not _sql_equal(a, b),
    ex.CompareOp.LT: lambda a, b: a < b,
    ex.CompareOp.LTE: lambda a, b: a <= b,
    ex.CompareOp.GT: lambda a, b: a > b,
    ex.CompareOp.GTE: lambda a, b: a >= b,
}


def _date_to_ts(days: int) -> int:
    return days * 86_400_000


_TS_PSEUDO = ("ROWTIME", "WINDOWSTART", "WINDOWEND")


def _is_ts_pseudo_ref(e) -> bool:
    return isinstance(e, ex.ColumnRef) and (
        e.name in _TS_PSEUDO or e.name.endswith(("_ROWTIME", "_WINDOWSTART", "_WINDOWEND"))
    )


def _to_decimal(v: Any) -> _decimal.Decimal:
    if isinstance(v, _decimal.Decimal):
        return v
    if isinstance(v, float):
        return _decimal.Decimal(repr(v))
    return _decimal.Decimal(v)


def _between_coercer(vt: Optional[SqlType], bt: Optional[SqlType]):
    """Bound coercion for BETWEEN, mirroring comparison coercions."""
    if vt is None or bt is None:
        return None
    temporal = {SqlBaseType.TIMESTAMP: _parse_timestamp_text,
                SqlBaseType.TIME: _parse_time_text,
                SqlBaseType.DATE: _parse_date_text}
    if vt.base in temporal and bt.base == SqlBaseType.STRING:
        return temporal[vt.base]
    if vt.base == SqlBaseType.TIMESTAMP and bt.base == SqlBaseType.DATE:
        return _date_to_ts
    if vt.base == SqlBaseType.DOUBLE and bt.base == SqlBaseType.DECIMAL:
        return float
    return None


def _sql_equal(a: Any, b: Any) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, _decimal.Decimal) and isinstance(b, float):
        return float(a) == b
    if isinstance(b, _decimal.Decimal) and isinstance(a, float):
        return a == float(b)
    return a == b


def _parse_bool_lenient(s: Any):
    """SqlBooleans.parseBoolean: case-insensitive prefixes of true/yes ->
    True, false/no -> False, else None."""
    if isinstance(s, bool):
        return s
    t = str(s).strip().lower()
    if t and ("true".startswith(t) or "yes".startswith(t)):
        return True
    if t and ("false".startswith(t) or "no".startswith(t)):
        return False
    return None


def _number_to_string(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


_IN_NO_MATCH = object()


def _in_equal(a: Any, b: Any) -> bool:
    """IN-list equality with cross-type literal coercion (reference
    InPredicate over coerced values): arrays/maps/structs recurse, strings
    compare numerically/boolean-ly against the other side when types differ."""
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(_in_equal(x, y) for x, y in zip(a, b))
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(_in_equal(a[k], b[k]) for k in a)
    if isinstance(a, str) != isinstance(b, str):
        s, o = (a, b) if isinstance(a, str) else (b, a)
        if isinstance(o, bool):
            return _parse_bool_lenient(s) is o
        if isinstance(o, (int, float)):
            try:
                return float(s) == float(o)
            except ValueError:
                return False
        return False
    return _sql_equal(a, b)


def _like_to_regex(pattern: str, escape: Optional[str]) -> re.Pattern:
    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if escape and c == escape and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if c == "%":
            out.append(".*")
        elif c == "_":
            out.append(".")
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("".join(out), re.DOTALL)


def _lambda_param_types(
    fname: str,
    arg_idx: int,
    arg_types: List[SqlType],
    compiled,
    lam: ex.LambdaExpression,
) -> List[SqlType]:
    """Structural typing for lambda params based on the collection arg."""
    coll_t = arg_types[0]
    n = len(lam.params)
    if coll_t is None:
        return [T.STRING] * n
    if coll_t.base == SqlBaseType.ARRAY:
        el = coll_t.element or T.STRING
        if fname == "REDUCE":
            init_t = arg_types[1] if len(arg_types) > 1 else T.STRING
            return [init_t, el][:n]
        return [el] * n
    if coll_t.base == SqlBaseType.MAP:
        k = coll_t.key or T.STRING
        v = coll_t.element or T.STRING
        if fname == "REDUCE":
            init_t = arg_types[1] if len(arg_types) > 1 else T.STRING
            return [init_t, k, v][:n]
        return [k, v][:n]
    return [T.STRING] * n


# ------------------------------------------------------------------- casts


def make_caster(src: Optional[SqlType], target: SqlType) -> Callable[[Any], Any]:
    tb = target.base
    sb = src.base if src is not None else None

    if tb == SqlBaseType.STRING:
        if sb == SqlBaseType.DATE:
            return _date_to_iso
        if sb == SqlBaseType.TIME:
            return _time_to_iso
        if sb == SqlBaseType.TIMESTAMP:
            return _ts_to_iso
        if sb == SqlBaseType.STRUCT:
            # Kafka Connect Struct.toString: Struct{f=v,...}, no spaces
            return lambda v: (
                "Struct{"
                + ",".join(
                    f"{k}={_cast_to_string(x)}"
                    for k, x in v.items()
                    if x is not None
                )
                + "}"
            )
        return _cast_to_string
    if tb in (SqlBaseType.INTEGER, SqlBaseType.BIGINT):
        bits = 32 if tb == SqlBaseType.INTEGER else 64
        half = 1 << (bits - 1)
        full = 1 << bits
        def to_int(v):
            if isinstance(v, bool):
                raise FunctionException("cannot cast BOOLEAN to INT")
            if isinstance(v, str):
                v = float(v) if "." in v or "e" in v.lower() else int(v)
            if isinstance(v, float):
                # Java double->int/long conversion saturates (JLS 5.1.3):
                # NaN -> 0, +/-inf and out-of-range clamp to MIN/MAX
                if math.isnan(v):
                    return 0
                if v >= half:
                    return half - 1
                if v < -half:
                    return -half
                return math.trunc(v)
            n = math.trunc(v)
            # integral narrowing (BIGINT/DECIMAL source) wraps two's-complement
            # (e.g. 2147483648 -> -2147483648)
            return (n + half) % full - half
        return to_int
    if tb == SqlBaseType.DOUBLE:
        def to_double(v):
            if isinstance(v, bool):
                raise FunctionException("cannot cast BOOLEAN to DOUBLE")
            return float(v)
        return to_double
    if tb == SqlBaseType.DECIMAL:
        scale = target.scale or 0
        precision = target.precision or scale
        quantum = _decimal.Decimal(1).scaleb(-scale)
        limit = _decimal.Decimal(10) ** (precision - scale)
        def to_dec(v):
            if isinstance(v, bool):
                raise FunctionException("cannot cast BOOLEAN to DECIMAL")
            try:
                d = _to_decimal(v.strip() if isinstance(v, str) else v)
            except _decimal.InvalidOperation:
                raise FunctionException(f"cannot cast {v!r} to DECIMAL") from None
            # HALF_UP = ties away from zero (Java BigDecimal)
            out = d.quantize(quantum, rounding=_decimal.ROUND_HALF_UP)
            if abs(out) >= limit:
                raise FunctionException(
                    f"Numeric field overflow: A field with precision {precision} "
                    f"and scale {scale} must round to an absolute value less "
                    f"than 10^{precision - scale}. Got {v}"
                )
            return out
        return to_dec
    if tb == SqlBaseType.BOOLEAN:
        def to_bool(v):
            if isinstance(v, bool):
                return v
            if isinstance(v, str):
                s = v.strip().lower()
                if s in ("true", "yes", "t", "y"):
                    return True
                if s in ("false", "no", "f", "n"):
                    return False
                return None
            raise FunctionException(f"cannot cast {type(v).__name__} to BOOLEAN")
        return to_bool
    if tb == SqlBaseType.TIMESTAMP:
        date_src = sb == SqlBaseType.DATE
        def to_ts(v):
            if isinstance(v, str):
                return _parse_timestamp_text(v)
            if isinstance(v, (int, float)):
                return _date_to_ts(int(v)) if date_src else int(v)
            raise FunctionException("cannot cast to TIMESTAMP")
        return to_ts
    if tb == SqlBaseType.DATE:
        ts_src = sb == SqlBaseType.TIMESTAMP
        def to_date(v):
            if isinstance(v, str):
                return _parse_date_text(v)
            if isinstance(v, int):
                return v // 86_400_000 if ts_src else v
            raise FunctionException("cannot cast to DATE")
        return to_date
    if tb == SqlBaseType.TIME:
        ts_src = sb == SqlBaseType.TIMESTAMP
        def to_time(v):
            if isinstance(v, str):
                return _parse_time_text(v)
            if isinstance(v, int):
                return v % 86_400_000 if ts_src else v
            raise FunctionException("cannot cast to TIME")
        return to_time
    if tb == SqlBaseType.ARRAY:
        if src is not None and src.base != SqlBaseType.ARRAY:
            raise FunctionException(f"Cast of {src} to {target} is not supported")
        el_cast = make_caster(src.element if src else None, target.element)
        return lambda v: [None if x is None else el_cast(x) for x in v]
    if tb == SqlBaseType.MAP:
        if src is not None and src.base != SqlBaseType.MAP:
            raise FunctionException(f"Cast of {src} to {target} is not supported")
        v_cast = make_caster(src.element if src else None, target.element)
        return lambda v: {k: (None if x is None else v_cast(x)) for k, x in v.items()}
    if tb == SqlBaseType.STRUCT:
        if src is not None and src.base != SqlBaseType.STRUCT:
            raise FunctionException(f"Cast of {src} to {target} is not supported")
        field_casts = {}
        src_fields = dict(src.fields or ()) if src and src.fields else {}
        for nm, ft in target.fields or ():
            field_casts[nm] = make_caster(src_fields.get(nm), ft)
        def to_struct(v):
            return {
                nm: (None if v.get(nm) is None else field_casts[nm](v.get(nm)))
                for nm in field_casts
            }
        return to_struct
    if tb == SqlBaseType.BYTES:
        def to_bytes(v):
            if isinstance(v, bytes):
                return v
            if isinstance(v, str):
                import base64

                return base64.b64decode(v)
            raise FunctionException("cannot cast to BYTES")
        return to_bytes
    raise FunctionException(f"unsupported cast target {target}")


def _date_to_iso(days: int) -> str:
    import datetime as dt

    return (dt.date(1970, 1, 1) + dt.timedelta(days=days)).isoformat()


def _time_to_iso(ms: int) -> str:
    s, ms_part = divmod(int(ms), 1000)
    h, rem = divmod(s, 3600)
    m, sec = divmod(rem, 60)
    base = f"{h:02d}:{m:02d}:{sec:02d}"
    return base + (f".{ms_part:03d}" if ms_part else "")


def _ts_to_iso(ms: int) -> str:
    import datetime as dt

    d = dt.datetime.fromtimestamp(ms / 1000.0, dt.timezone.utc)
    return d.strftime("%Y-%m-%dT%H:%M:%S.") + f"{int(ms) % 1000:03d}"


def java_double_str(v: float) -> str:
    """java.lang.Double.toString: positional notation in [1e-3, 1e7),
    otherwise scientific with a [1,10) mantissa, uppercase E, no '+' on the
    exponent.  Digits come from Python's shortest round-trip repr (the two
    agree except for Double.MIN_VALUE, special-cased)."""
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "Infinity"
    if v == float("-inf"):
        return "-Infinity"
    if v == 0.0:
        return "-0.0" if math.copysign(1.0, v) < 0 else "0.0"
    sign = "-" if v < 0 else ""
    a = abs(v)
    if a == 5e-324:
        return sign + "4.9E-324"  # FloatingDecimal's digits for MIN_VALUE
    if 1e-3 <= a < 1e7:
        s = repr(a)
        if "e" in s or "E" in s:  # repr may go scientific near the edges
            d = _decimal.Decimal(s)
            s = format(d, "f")
        if "." not in s:
            s += ".0"
        return sign + s
    # scientific: mantissa digits from the shortest repr
    d = _decimal.Decimal(repr(a))
    exp10 = d.adjusted()
    digits = "".join(str(x) for x in d.as_tuple().digits)
    mant = digits[0] + "." + (digits[1:] or "0")
    return f"{sign}{mant}E{exp10}"


def _cast_to_string(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, _decimal.Decimal):
        return format(v, "f")
    if isinstance(v, float):
        return java_double_str(v)
    if isinstance(v, bytes):
        import base64

        return base64.b64encode(v).decode("ascii")
    if isinstance(v, list):
        return "[" + ", ".join(_cast_to_string(x) if x is not None else "null" for x in v) + "]"
    if isinstance(v, dict):
        return "{" + ", ".join(f"{k}={_cast_to_string(x) if x is not None else 'null'}" for k, x in v.items()) + "}"
    return str(v)


def _parse_timestamp_text(text: str) -> int:
    import datetime as dt

    t = text.strip().replace("T", " ")
    # trailing zone: Z, or a numeric offset — only when a time-of-day part is
    # present (a bare "2024-05-10" must not lose its day to a "-10" offset)
    tz = dt.timezone.utc
    m = re.search(r"(Z|[+-]\d{2}:?\d{2}|[+-]\d{2})$", t)
    if m and (m.group(1) == "Z" or ":" in t):
        z = m.group(1)
        if z != "Z":
            sign = 1 if z[0] == "+" else -1
            digits = z[1:].replace(":", "")
            hh = int(digits[:2])
            mm = int(digits[2:4]) if len(digits) >= 4 else 0
            tz = dt.timezone(sign * dt.timedelta(hours=hh, minutes=mm))
        t = t[: m.start()].rstrip()
    for fmt in (
        "%Y-%m-%d %H:%M:%S.%f",
        "%Y-%m-%d %H:%M:%S",
        "%Y-%m-%d %H:%M",
        "%Y-%m-%d %H",
        "%Y-%m-%d",
        "%Y-%m",
        "%Y",
    ):
        try:
            d = dt.datetime.strptime(t, fmt).replace(tzinfo=tz)
            return int(d.timestamp() * 1000)
        except ValueError:
            continue
    raise FunctionException(f"cannot parse timestamp {text!r}")


def _parse_date_text(text: str) -> int:
    import datetime as dt

    t = text.strip()
    # partial ISO forms parse like Java's SqlTimeTypes ("1970-01" -> first
    # of month, "1970" -> Jan 1)
    if re.fullmatch(r"\d{4}", t):
        t = f"{t}-01-01"
    elif re.fullmatch(r"\d{4}-\d{2}", t):
        t = f"{t}-01"
    return (dt.date.fromisoformat(t) - dt.date(1970, 1, 1)).days


def _parse_time_text(text: str) -> int:
    import datetime as dt

    t = text.strip()
    for fmt in ("%H:%M:%S.%f", "%H:%M:%S", "%H:%M"):
        try:
            d = dt.datetime.strptime(t, fmt)
            return (d.hour * 3600 + d.minute * 60 + d.second) * 1000 + d.microsecond // 1000
        except ValueError:
            continue
    raise FunctionException(f"cannot parse time {text!r}")
