"""Binary Avro codec + Confluent wire framing.

Byte-level implementation of the Avro 1.11 binary encoding (spec §
"Binary Encoding"): zigzag varints, single-block arrays/maps, union branch
indexes, logical types (decimal on bytes/fixed, date, time-millis,
timestamp-millis).  The reference's serde does the same work through
io.confluent AvroConverter + KsqlAvroSerdeFactory
(ksqldb-serde/src/main/java/io/confluent/ksql/serde/avro/AvroFormat.java,
AvroSRSchemaDataTranslator.java); this module is the from-scratch
equivalent, wired to the in-process schema registry through the Confluent
framing: [magic 0x00][schema id, 4-byte big-endian][avro binary payload].

Schemas are the parsed JSON objects the schema-registry subsystem already
stores; named-type references resolve through an environment accumulated
during traversal.
"""

from __future__ import annotations

import io
import struct
from typing import Any, Dict, List, Optional, Tuple

from ksql_tpu.common.errors import SerdeException

MAGIC = b"\x00"


# ----------------------------------------------------------- primitive io


def write_long(out: io.BytesIO, v: int) -> None:
    """Zigzag varint (spec: int and long share the encoding)."""
    v = (v << 1) ^ (v >> 63)
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def read_long(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        raw = buf.read(1)
        if not raw:
            raise SerdeException("truncated Avro varint")
        b = raw[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


# ------------------------------------------------------------ schema utils


def _schema_type(schema: Any) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


def _named(schema: Any) -> Optional[str]:
    if isinstance(schema, dict) and "name" in schema:
        ns = schema.get("namespace")
        name = schema["name"]
        if "." in name or not ns:
            return name
        return f"{ns}.{name}"
    return None


def _collect_names(schema: Any, env: Dict[str, Any]) -> None:
    if isinstance(schema, list):
        for s in schema:
            _collect_names(s, env)
        return
    if not isinstance(schema, dict):
        return
    n = _named(schema)
    if n is not None and schema.get("type") in ("record", "enum", "fixed"):
        env[n] = schema
        env[schema["name"]] = schema  # short name too
    t = schema.get("type")
    if t == "record":
        for f in schema.get("fields", ()):
            _collect_names(f.get("type"), env)
    elif t == "array":
        _collect_names(schema.get("items"), env)
    elif t == "map":
        _collect_names(schema.get("values"), env)


def _resolve(schema: Any, env: Dict[str, Any]) -> Any:
    if isinstance(schema, str) and schema in env:
        return env[schema]
    return schema


_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double", "bytes", "string"}


def _union_branch(schema: List[Any], value: Any, env: Dict[str, Any]) -> Tuple[int, Any]:
    """Pick the union branch for a Python value."""
    def matches(s: Any) -> bool:
        s = _resolve(s, env)
        t = _schema_type(s)
        if value is None:
            return t == "null"
        if isinstance(value, bool):
            return t == "boolean"
        if isinstance(value, int):
            return t in ("int", "long", "float", "double")
        if isinstance(value, float):
            return t in ("double", "float")
        if isinstance(value, str):
            return t in ("string", "enum")
        if isinstance(value, (bytes, bytearray)):
            return t in ("bytes", "fixed")
        if isinstance(value, dict):
            if t == "record":
                # structural check so unions of records disambiguate
                names = {f["name"] for f in s.get("fields", ())}
                return all(k in names for k in value)
            return t == "map"
        if isinstance(value, (list, tuple)):
            return t == "array"
        import decimal

        if isinstance(value, decimal.Decimal):
            return t in ("bytes", "fixed", "double", "float")
        return False

    for i, s in enumerate(schema):
        if matches(s):
            return i, s
    raise SerdeException(f"no union branch for {type(value).__name__} in {schema}")


# ----------------------------------------------------------------- encode


def encode(schema: Any, value: Any, env: Optional[Dict[str, Any]] = None) -> bytes:
    if env is None:
        env = {}
        _collect_names(schema, env)
    out = io.BytesIO()
    _encode(out, schema, value, env)
    return out.getvalue()


def _to_unscaled(value: Any, scale: int) -> int:
    import decimal

    d = value if isinstance(value, decimal.Decimal) else decimal.Decimal(str(value))
    q = d.quantize(decimal.Decimal(1).scaleb(-scale))
    return int(q.scaleb(scale))


def _encode(out: io.BytesIO, schema: Any, value: Any, env: Dict[str, Any]) -> None:
    schema = _resolve(schema, env)
    if isinstance(schema, list):
        i, branch = _union_branch(schema, value, env)
        write_long(out, i)
        _encode(out, branch, value, env)
        return
    t = _schema_type(schema)
    logical = schema.get("logicalType") if isinstance(schema, dict) else None
    if t == "null":
        if value is not None:
            raise SerdeException(f"non-null value for null schema: {value!r}")
        return
    if value is None:
        raise SerdeException(f"null value for non-nullable {t}")
    if t == "boolean":
        out.write(b"\x01" if value else b"\x00")
    elif t in ("int", "long"):
        # logical date/time-millis/timestamp-millis are already integral
        write_long(out, int(value))
    elif t == "float":
        out.write(struct.pack("<f", float(value)))
    elif t == "double":
        out.write(struct.pack("<d", float(value)))
    elif t == "bytes":
        if logical == "decimal":
            unscaled = _to_unscaled(value, int(schema.get("scale", 0)))
            nbytes = max(1, (unscaled.bit_length() + 8) // 8)
            data = unscaled.to_bytes(nbytes, "big", signed=True)
        else:
            data = bytes(value)
        write_long(out, len(data))
        out.write(data)
    elif t == "string":
        data = str(value).encode("utf-8")
        write_long(out, len(data))
        out.write(data)
    elif t == "fixed":
        size = int(schema["size"])
        if logical == "decimal":
            unscaled = _to_unscaled(value, int(schema.get("scale", 0)))
            data = unscaled.to_bytes(size, "big", signed=True)
        else:
            data = bytes(value)
            if len(data) != size:
                raise SerdeException(
                    f"fixed({size}) got {len(data)} bytes"
                )
        out.write(data)
    elif t == "enum":
        symbols = schema["symbols"]
        try:
            write_long(out, symbols.index(value))
        except ValueError:
            raise SerdeException(f"{value!r} not in enum {symbols}") from None
    elif t == "array":
        items = schema["items"]
        seq = list(value)
        if seq:
            write_long(out, len(seq))
            for item in seq:
                _encode(out, items, item, env)
        write_long(out, 0)
    elif t == "map":
        values_schema = schema["values"]
        entries = list(value.items())
        if entries:
            write_long(out, len(entries))
            for k, v in entries:
                kd = str(k).encode("utf-8")
                write_long(out, len(kd))
                out.write(kd)
                _encode(out, values_schema, v, env)
        write_long(out, 0)
    elif t == "record":
        _collect_names(schema, env)
        lookup = {k.upper(): v for k, v in value.items()} if value else {}
        for f in schema.get("fields", ()):
            fv = lookup.get(f["name"].upper())
            if fv is None and "default" in f:
                # absent field, or a null for a non-optional field with a
                # schema default (Connect AvroData substitutes the default)
                ft = _resolve(f["type"], env)
                nullable = isinstance(ft, list) and any(
                    _schema_type(b) == "null" for b in ft
                )
                if f["name"].upper() not in lookup or not nullable:
                    fv = f["default"]
            _encode(out, f["type"], fv, env)
    else:
        raise SerdeException(f"unsupported Avro type {t!r}")


# ----------------------------------------------------------------- decode


def decode(schema: Any, payload: bytes, env: Optional[Dict[str, Any]] = None) -> Any:
    if env is None:
        env = {}
        _collect_names(schema, env)
    buf = io.BytesIO(payload)
    value = _decode(buf, schema, env)
    return value


def _decode(buf: io.BytesIO, schema: Any, env: Dict[str, Any]) -> Any:
    schema = _resolve(schema, env)
    if isinstance(schema, list):
        i = read_long(buf)
        if not 0 <= i < len(schema):
            raise SerdeException(f"union branch {i} out of range")
        return _decode(buf, schema[i], env)
    t = _schema_type(schema)
    logical = schema.get("logicalType") if isinstance(schema, dict) else None
    if t == "null":
        return None
    if t == "boolean":
        raw = buf.read(1)
        if not raw:
            raise SerdeException("truncated boolean")
        return raw[0] != 0
    if t in ("int", "long"):
        return read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        n = read_long(buf)
        data = buf.read(n)
        if logical == "decimal":
            import decimal

            unscaled = int.from_bytes(data, "big", signed=True)
            return decimal.Decimal(unscaled).scaleb(-int(schema.get("scale", 0)))
        return data
    if t == "string":
        n = read_long(buf)
        return buf.read(n).decode("utf-8")
    if t == "fixed":
        data = buf.read(int(schema["size"]))
        if logical == "decimal":
            import decimal

            unscaled = int.from_bytes(data, "big", signed=True)
            return decimal.Decimal(unscaled).scaleb(-int(schema.get("scale", 0)))
        return data
    if t == "enum":
        return schema["symbols"][read_long(buf)]
    if t == "array":
        out: List[Any] = []
        while True:
            n = read_long(buf)
            if n == 0:
                break
            if n < 0:  # block with byte-size prefix
                n = -n
                read_long(buf)
            for _ in range(n):
                out.append(_decode(buf, schema["items"], env))
        return out
    if t == "map":
        m: Dict[str, Any] = {}
        while True:
            n = read_long(buf)
            if n == 0:
                break
            if n < 0:
                n = -n
                read_long(buf)
            for _ in range(n):
                klen = read_long(buf)
                k = buf.read(klen).decode("utf-8")
                m[k] = _decode(buf, schema["values"], env)
        return m
    if t == "record":
        _collect_names(schema, env)
        out_rec: Dict[str, Any] = {}
        for f in schema.get("fields", ()):
            out_rec[f["name"]] = _decode(buf, f["type"], env)
        return out_rec
    raise SerdeException(f"unsupported Avro type {t!r}")


# --------------------------------------------------- Confluent wire framing


def frame(schema_id: int, payload: bytes) -> bytes:
    """[0x00][4-byte BE schema id][payload] (AbstractKafkaSchemaSerDe)."""
    return MAGIC + struct.pack(">I", schema_id) + payload


def unframe(data: bytes) -> Tuple[int, bytes]:
    if len(data) < 5 or data[:1] != MAGIC:
        raise SerdeException("payload is not Confluent-framed Avro")
    return struct.unpack(">I", data[1:5])[0], data[5:]


def is_framed(data: Any) -> bool:
    return isinstance(data, (bytes, bytearray)) and len(data) >= 5 and data[:1] == MAGIC


# ------------------------------------------------------ SQL schema bridge


def sql_to_avro_schema(columns, name: str = "KsqlDataSourceSchema") -> Dict[str, Any]:
    """Build a writer schema from SQL value columns (the reference's
    AvroSchemas / connect-avro-converter translation, nullable unions)."""
    from ksql_tpu.common.types import SqlBaseType

    def of(t) -> Any:
        b = t.base
        if b == SqlBaseType.BOOLEAN:
            return ["null", "boolean"]
        if b == SqlBaseType.INTEGER:
            return ["null", "int"]
        if b == SqlBaseType.BIGINT:
            return ["null", "long"]
        if b == SqlBaseType.DOUBLE:
            return ["null", "double"]
        if b == SqlBaseType.STRING:
            return ["null", "string"]
        if b == SqlBaseType.BYTES:
            return ["null", "bytes"]
        if b == SqlBaseType.DECIMAL:
            return [
                "null",
                {
                    "type": "bytes",
                    "logicalType": "decimal",
                    "precision": t.precision,
                    "scale": t.scale,
                },
            ]
        if b == SqlBaseType.DATE:
            return ["null", {"type": "int", "logicalType": "date"}]
        if b == SqlBaseType.TIME:
            return ["null", {"type": "int", "logicalType": "time-millis"}]
        if b == SqlBaseType.TIMESTAMP:
            return ["null", {"type": "long", "logicalType": "timestamp-millis"}]
        if b == SqlBaseType.ARRAY:
            return ["null", {"type": "array", "items": of(t.element)}]
        if b == SqlBaseType.MAP:
            return ["null", {"type": "map", "values": of(t.value)}]
        if b == SqlBaseType.STRUCT:
            return [
                "null",
                {
                    "type": "record",
                    "name": f"{name}_{t.fields and t.fields[0][0] or 'S'}",
                    "fields": [
                        {"name": fn, "type": of(ft), "default": None}
                        for fn, ft in (t.fields or ())
                    ],
                },
            ]
        raise SerdeException(f"no Avro mapping for {t}")

    return {
        "type": "record",
        "name": name,
        "fields": [
            {"name": c.name, "type": of(c.type), "default": None}
            for c in columns
        ],
    }
