"""Serde formats: value/key (de)serialization.

Analog of ksqldb-serde (Format.java:41, FormatFactory.java:51,
GenericRowSerDe/GenericKeySerDe).  Formats implemented natively: JSON,
DELIMITED (CSV), KAFKA (primitive binary), NONE.  AVRO/PROTOBUF/JSON_SR
currently alias to schema'd JSON (documented deviation: the wire format
differs but the logical row round-trip is exact; a real schema-registry
format can slot in behind the same interface).
"""

from __future__ import annotations

import base64
import decimal as _decimal
import json
import math
import re
import struct
from typing import Any, Dict, List, Optional, Tuple

from ksql_tpu.common import faults
from ksql_tpu.common.errors import SerdeException
from ksql_tpu.common.schema import Column, LogicalSchema
from ksql_tpu.common.types import SqlBaseType, SqlType


class Format:
    name = "NONE"

    def serialize(self, row: Optional[Dict[str, Any]], columns: List[Column]) -> Any:
        raise NotImplementedError

    def deserialize(self, payload: Any, columns: List[Column]) -> Optional[Dict[str, Any]]:
        raise NotImplementedError


def _coerce(value: Any, t: SqlType) -> Any:
    """Coerce a JSON-decoded value into the SQL type's host representation."""
    if value is None:
        return None
    b = t.base
    if b == SqlBaseType.BOOLEAN:
        if isinstance(value, bool):
            return value
        if isinstance(value, str):
            return value.lower() == "true"
        return bool(value)
    if b in (SqlBaseType.INTEGER, SqlBaseType.BIGINT):
        if isinstance(value, bool):
            raise SerdeException(f"cannot coerce boolean to {t}")
        if isinstance(value, float):
            # Connect's Number.intValue()/longValue(): truncate toward zero
            return int(value)
        return int(value)
    if b in (SqlBaseType.DOUBLE,):
        if isinstance(value, bool):
            raise SerdeException(f"cannot coerce boolean to {t}")
        return float(value)
    if b == SqlBaseType.DECIMAL:
        if isinstance(value, bool):
            raise SerdeException(f"cannot coerce boolean to {t}")
        try:
            d = (
                value
                if isinstance(value, _decimal.Decimal)
                else _decimal.Decimal(
                    repr(value) if isinstance(value, float) else str(value)
                )
            )
        except _decimal.InvalidOperation:
            raise SerdeException(f"cannot coerce {value!r} to {t}") from None
        quantum = _decimal.Decimal(1).scaleb(-(t.scale or 0))
        return d.quantize(quantum, rounding=_decimal.ROUND_HALF_UP)
    if b == SqlBaseType.STRING:
        if isinstance(value, bool):
            return "true" if value else "false"
        if isinstance(value, (dict, list)):
            return json.dumps(value, separators=(",", ":"))
        return str(value)
    if b == SqlBaseType.BYTES:
        if isinstance(value, bytes):
            return value
        return base64.b64decode(value)
    if b == SqlBaseType.TIMESTAMP:
        if isinstance(value, str):
            if re.fullmatch(r"-?\d+", value.strip()):
                return int(value)  # epoch-ms rendered as text (Avro/Connect)
            from ksql_tpu.execution.interpreter import _parse_timestamp_text

            return _parse_timestamp_text(value)
        return int(value)
    if b == SqlBaseType.DATE:
        if isinstance(value, str):
            if re.fullmatch(r"-?\d+", value.strip()):
                return int(value)  # epoch-days rendered as text
            from ksql_tpu.execution.interpreter import _parse_date_text

            return _parse_date_text(value)
        return int(value)
    if b == SqlBaseType.TIME:
        if isinstance(value, str):
            if re.fullmatch(r"-?\d+", value.strip()):
                return int(value)  # ms-of-day rendered as text
            from ksql_tpu.execution.interpreter import _parse_time_text

            return _parse_time_text(value)
        return int(value)
    if b == SqlBaseType.ARRAY:
        if not isinstance(value, list):
            raise SerdeException(f"cannot coerce {type(value).__name__} to {t}")
        return [_coerce(v, t.element) for v in value]
    if b == SqlBaseType.MAP:
        if not isinstance(value, dict):
            raise SerdeException(f"cannot coerce {type(value).__name__} to {t}")
        return {k: _coerce(v, t.element) for k, v in value.items()}
    if b == SqlBaseType.STRUCT:
        if not isinstance(value, dict):
            raise SerdeException(f"cannot coerce {type(value).__name__} to {t}")
        fields = dict(t.fields or ())
        lower = {k.upper(): v for k, v in value.items()}
        return {name: _coerce(lower.get(name.upper()), ft) for name, ft in fields.items()}
    raise SerdeException(f"unsupported type {t}")


def decimal_str(v: Any, t: SqlType) -> str:
    """Plain fixed-point rendering at the column's scale (the reference
    serializes BigDecimal.toPlainString — no zero-padding of the integer
    part, e.g. DECIMAL(5,3) 1 -> "1.000")."""
    scale = t.scale or 0
    return f"{v:.{scale}f}" if scale else str(int(v))


def _jsonable(value: Any, t: Optional[SqlType] = None, decimal_as_string: bool = False) -> Any:
    if value is None:
        return None
    if isinstance(value, bytes):
        return base64.b64encode(value).decode("ascii")
    if (
        t is not None
        and t.base == SqlBaseType.DECIMAL
        and isinstance(value, _decimal.Decimal)
        and value.adjusted() + 1 > (t.precision or 38) - (t.scale or 0)
        and value != 0
    ):
        # aggregate values past the declared precision fail the query, as
        # BigDecimal.setScale/DecimalUtil.ensureFit does (sum overflow)
        raise SerdeException(
            f"Numeric field overflow: value {value} does not fit {t}"
        )
    if (
        decimal_as_string
        and t is not None
        and t.base == SqlBaseType.DECIMAL
        and isinstance(value, (int, float, _decimal.Decimal))
        and not isinstance(value, bool)
    ):
        return decimal_str(value, t)
    if isinstance(value, _decimal.Decimal):
        # plain-JSON decimals emit as numbers (double range)
        return int(value) if value == value.to_integral_value() and (t is None or (t.scale or 0) == 0) else float(value)
    if isinstance(value, float):
        # Jackson writes non-finite doubles as NaN/Infinity tokens; QTT
        # expected files carry them as strings
        if value != value:
            return "NaN"
        if value == float("inf"):
            return "Infinity"
        if value == float("-inf"):
            return "-Infinity"
        return value
    if isinstance(value, dict):
        if t is not None and t.base == SqlBaseType.STRUCT:
            fts = dict(t.fields or ())
            return {k: _jsonable(v, fts.get(k), decimal_as_string)
                    for k, v in value.items()}
        et = t.element if t is not None and t.base == SqlBaseType.MAP else None
        return {k: _jsonable(v, et, decimal_as_string) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        et = t.element if t is not None and t.base == SqlBaseType.ARRAY else None
        return [_jsonable(v, et, decimal_as_string) for v in value]
    return value


class JsonFormat(Format):
    name = "JSON"
    decimal_as_string = False  # AVRO renders decimals as padded strings

    def __init__(self, wrap: bool = True):
        # wrap=False = SerdeFeature.UNWRAP_SINGLES: a single column is
        # (de)serialized as the bare value, no envelope (SerdeUtils.java:63)
        self.wrap = wrap

    def serialize(self, row, columns):
        if row is None:
            return None
        das = self.decimal_as_string
        if not self.wrap and len(columns) == 1:
            return json.dumps(
                _jsonable(row.get(columns[0].name), columns[0].type, das),
                separators=(",", ":"),
            )
        return json.dumps(
            {c.name: _jsonable(row.get(c.name), c.type, das) for c in columns},
            separators=(",", ":"),
        )

    def deserialize(self, payload, columns):
        if payload is None:
            return None
        if isinstance(payload, (str, bytes, bytearray)):
            try:
                obj = json.loads(payload)
            except ValueError:
                if (
                    not self.wrap
                    and len(columns) == 1
                    and columns[0].type.base == SqlBaseType.STRING
                ):
                    # unwrapped single string values arrive as raw text
                    obj = payload if isinstance(payload, str) else payload.decode()
                else:
                    raise
        else:
            obj = payload
        if not self.wrap and len(columns) == 1:
            return {columns[0].name: _coerce(obj, columns[0].type)}
        if not isinstance(obj, dict):
            # single-column anonymous value
            if len(columns) == 1:
                return {columns[0].name: _coerce(obj, columns[0].type)}
            raise SerdeException(f"expected JSON object, got {type(obj).__name__}")
        upper = {k.upper(): v for k, v in obj.items()}
        return {c.name: _coerce(upper.get(c.name.upper()), c.type) for c in columns}


class AvroFormat(JsonFormat):
    """AVRO in two tiers:

    * registry-wired **binary** tier: with a schema registry + subject the
      serde writes real Confluent-framed Avro binary (magic 0 + schema id +
      avro binary body, serde/avro_binary.py) and reads framed payloads
      back through the registry by id — the byte-level analog of
      ksqldb-serde/.../avro/AvroFormat.java + AvroConverter;
    * logical tier (no registry): JSON envelope with Avro's decimal
      rendering (fixed-scale padded strings), which is what the in-process
      QTT topics carry.

    deserialize() auto-detects framing, so both tiers coexist on a topic.
    """

    name = "AVRO"
    decimal_as_string = True

    def __init__(self, wrap: bool = True, registry=None, subject: Optional[str] = None):
        super().__init__(wrap)
        self.registry = registry
        self.subject = subject

    _writer_cache: Optional[Tuple[int, Any]] = None

    def _writer_schema(self, columns):
        import json as _json

        from ksql_tpu.serde import avro_binary as ab

        if self._writer_cache is not None:
            return self._writer_cache  # one registration per serde instance
        reg = self.registry.latest(self.subject) if self.subject else None
        if reg is not None and reg.schema_type == "AVRO":
            schema = reg.schema
            if isinstance(schema, str):
                schema = _json.loads(schema)
            self._writer_cache = (reg.schema_id, schema)
        else:
            schema = ab.sql_to_avro_schema(columns)
            sid = self.registry.register(
                self.subject or "anonymous-value", "AVRO", schema
            )
            self._writer_cache = (sid, schema)
        return self._writer_cache

    def serialize(self, row, columns):
        if self.registry is None:
            return super().serialize(row, columns)
        if row is None:
            return None
        from ksql_tpu.serde import avro_binary as ab

        sid, schema = self._writer_schema(columns)
        value = {c.name: row.get(c.name) for c in columns}
        if not self.wrap and len(columns) == 1:
            value = value[columns[0].name]
        return ab.frame(sid, ab.encode(schema, value))

    def deserialize(self, payload, columns):
        from ksql_tpu.serde import avro_binary as ab

        if self.registry is not None and ab.is_framed(payload):
            import json as _json

            sid, body = ab.unframe(bytes(payload))
            reg = self.registry.get_by_id(sid)
            if reg is None:
                raise SerdeException(f"unknown schema id {sid}")
            schema = reg.schema
            if isinstance(schema, str):
                schema = _json.loads(schema)
            obj = ab.decode(schema, body)
            if not self.wrap and len(columns) == 1:
                return {columns[0].name: _coerce(obj, columns[0].type)}
            if not isinstance(obj, dict):
                if len(columns) == 1:
                    return {columns[0].name: _coerce(obj, columns[0].type)}
                raise SerdeException(
                    f"expected Avro record, got {type(obj).__name__}"
                )
            upper = {k.upper(): v for k, v in obj.items()}
            return {c.name: _coerce(upper.get(c.name.upper()), c.type) for c in columns}
        return super().deserialize(payload, columns)


class DelimitedFormat(Format):
    name = "DELIMITED"

    def __init__(self, delimiter: str = ","):
        self.delimiter = delimiter

    def serialize(self, row, columns):
        if row is None:
            return None
        parts = []
        for i, c in enumerate(columns):
            v = row.get(c.name)
            if v is None:
                parts.append("")
            elif isinstance(v, bool):
                parts.append(self._quote("true" if v else "false", i == 0))
            elif isinstance(v, bytes):
                parts.append(self._quote(base64.b64encode(v).decode("ascii"), i == 0))
            elif (
                isinstance(v, (float, int, _decimal.Decimal))
                and c.type.base == SqlBaseType.DECIMAL
            ):
                parts.append(self._quote(decimal_str(v, c.type), i == 0))
            elif isinstance(v, float):
                from ksql_tpu.execution.interpreter import java_double_str

                parts.append(self._quote(java_double_str(v), i == 0))
            else:
                parts.append(self._quote(str(v), i == 0))
        return self.delimiter.join(parts)

    def _quote(self, s: str, first_field: bool) -> str:
        """commons-csv QuoteMode.MINIMAL quoting (the reference's CSVPrinter):
        quote on embedded delimiter/quote/newline; the first field of a record
        is also quoted when it starts with a non-alphanumeric character, other
        fields when their first character is <= '#'."""
        needs = self.delimiter in s or '"' in s or "\n" in s or "\r" in s
        if not needs:
            if not s:
                needs = first_field  # empty first field prints as ""
            else:
                ch = s[0]
                if first_field:
                    needs = not (ch.isascii() and ch.isalnum())
                else:
                    needs = ch <= "#"
                needs = needs or s[-1] <= " "  # trailing whitespace
        if needs:
            return '"' + s.replace('"', '""') + '"'
        return s

    def deserialize(self, payload, columns):
        if payload is None:
            return None
        text = payload.decode() if isinstance(payload, bytes) else str(payload)
        values = self._split(text)
        if len(values) != len(columns):
            raise SerdeException(
                f"Unexpected field count, csv line has {len(values)} columns, "
                f"schema has {len(columns)}"
            )
        out = {}
        for c, raw in zip(columns, values):
            if raw == "":
                out[c.name] = None
                continue
            b = c.type.base
            if b == SqlBaseType.BOOLEAN:
                out[c.name] = raw.strip().lower() == "true"
            elif b in (SqlBaseType.INTEGER, SqlBaseType.BIGINT):
                out[c.name] = int(raw)
            elif b == SqlBaseType.DOUBLE:
                out[c.name] = float(raw)
            elif b == SqlBaseType.DECIMAL:
                out[c.name] = _coerce(raw, c.type)
            elif b == SqlBaseType.STRING:
                out[c.name] = raw
            elif b == SqlBaseType.BYTES:
                out[c.name] = base64.b64decode(raw)
            elif b in (SqlBaseType.TIMESTAMP, SqlBaseType.DATE, SqlBaseType.TIME):
                out[c.name] = _coerce(raw if not raw.lstrip("-").isdigit() else int(raw), c.type)
            else:
                raise SerdeException(f"DELIMITED does not support type {c.type}")
        return out

    def _split(self, text: str) -> List[str]:
        out, cur, i, n = [], [], 0, len(text)
        in_quotes = False
        while i < n:
            ch = text[i]
            if in_quotes:
                if ch == '"':
                    if i + 1 < n and text[i + 1] == '"':
                        cur.append('"')
                        i += 2
                        continue
                    in_quotes = False
                else:
                    cur.append(ch)
            elif ch == '"':
                in_quotes = True
            elif ch == self.delimiter:
                out.append("".join(cur))
                cur = []
            else:
                cur.append(ch)
            i += 1
        out.append("".join(cur))
        return out


class KafkaFormat(Format):
    """Primitive binary format (KAFKA serde: int/bigint/double/string)."""

    name = "KAFKA"

    def serialize(self, row, columns):
        if row is None:
            return None
        if len(columns) != 1:
            # multi-column KAFKA keys serialize as a tuple of python values
            return tuple(row.get(c.name) for c in columns)
        v = row.get(columns[0].name)
        if v is None:
            return None
        b = columns[0].type.base
        # the in-process log carries native python values; the KAFKA format's
        # fixed-width binary encoding is applied only at a real wire boundary
        if b == SqlBaseType.INTEGER:
            return int(v)
        if b in (SqlBaseType.BIGINT, SqlBaseType.TIMESTAMP):
            return int(v)
        if b == SqlBaseType.DOUBLE:
            return float(v)
        if b in (SqlBaseType.STRING, SqlBaseType.BYTES):
            return v
        raise SerdeException(f"KAFKA format does not support {columns[0].type}")

    def deserialize(self, payload, columns):
        if payload is None:
            return None
        if isinstance(payload, tuple):
            return {c.name: v for c, v in zip(columns, payload)}
        if len(columns) != 1:
            raise SerdeException("KAFKA format supports single-column payloads")
        c = columns[0]
        b = c.type.base
        if isinstance(payload, (int, float, str, bool, list, dict)):
            # already-decoded (in-process producer path)
            return {c.name: _coerce(payload, c.type)}
        if b == SqlBaseType.INTEGER:
            return {c.name: struct.unpack(">i", payload)[0]}
        if b in (SqlBaseType.BIGINT, SqlBaseType.TIMESTAMP):
            return {c.name: struct.unpack(">q", payload)[0]}
        if b == SqlBaseType.DOUBLE:
            return {c.name: struct.unpack(">d", payload)[0]}
        if b == SqlBaseType.STRING:
            return {c.name: payload.decode("utf-8")}
        if b == SqlBaseType.BYTES:
            return {c.name: payload}
        raise SerdeException(f"KAFKA format does not support {c.type}")


def _proto3_default(v: Any, t: SqlType) -> Any:
    """proto3 scalars have no null: absent fields read back as their default
    (0 / "" / false / [] / {}); message-typed fields (struct, temporal and
    decimal well-knowns) stay null."""
    b = t.base
    if v is None:
        if b in (SqlBaseType.INTEGER, SqlBaseType.BIGINT):
            return 0
        if b == SqlBaseType.DOUBLE:
            return 0.0
        if b == SqlBaseType.BOOLEAN:
            return False
        if b == SqlBaseType.STRING:
            return ""
        if b == SqlBaseType.BYTES:
            # connect's protobuf translator maps bytes to optional -> null
            return None
        if b == SqlBaseType.ARRAY:
            return []
        if b == SqlBaseType.MAP:
            return {}
        return None
    if b == SqlBaseType.ARRAY:
        return [_proto3_default(x, t.element) for x in v]
    if b == SqlBaseType.MAP:
        return {k: _proto3_default(x, t.element) for k, x in v.items()}
    if b == SqlBaseType.STRUCT:
        fields = dict(t.fields or ())
        return {n: _proto3_default(v.get(n), ft) for n, ft in fields.items()}
    return v


class ProtobufFormat(JsonFormat):
    """PROTOBUF in two tiers (mirroring AvroFormat):

    * registry-wired **binary** tier: with a schema registry + subject the
      serde writes real Confluent-framed protobuf wire bytes (magic 0 +
      schema id + message-index path + proto3 body, serde/proto_binary.py)
      and reads framed payloads back through the registry by id — the
      byte-level analog of ksqldb-serde/.../protobuf/ProtobufFormat.java:31
      + ProtobufConverter;
    * logical tier (no registry): JSON envelope with proto3 default-value
      semantics, which is what the in-process QTT topics carry.

    ``nullable_all`` models VALUE_PROTOBUF_NULLABLE_REPRESENTATION
    (OPTIONAL/WRAPPER): scalar fields become nullable instead of defaulting
    (wrapper types on the wire).  ``float32`` lists fields whose wire type
    is single-precision ``float``: their values round-trip through float32.
    """

    name = "PROTOBUF"

    def __init__(self, wrap: bool = True, nullable_all: bool = False,
                 float32: tuple = (), registry=None, subject: Optional[str] = None,
                 full_name: Optional[str] = None):
        super().__init__(wrap)
        self.nullable_all = nullable_all
        self.float32 = frozenset(float32)
        self.registry = registry
        self.subject = subject
        self.full_name = full_name

    def _f32(self, out):
        if out and self.float32:
            for name in self.float32:
                for k in out:
                    if k.upper() == name.upper() and out[k] is not None:
                        out[k] = struct.unpack("<f", struct.pack("<f", float(out[k])))[0]
        return out

    # codec construction parses .proto text: cache per writer subject and
    # per reader schema id (this is the per-record serde hot path)
    _writer_cache: Optional[Tuple[int, Any, Tuple[int, ...]]] = None
    _reader_cache: Optional[Tuple[int, Any]] = None

    def _writer_codec(self, columns):
        from ksql_tpu.serde import proto_binary as pb

        if self._writer_cache is not None:
            return self._writer_cache
        reg = self.registry.latest(self.subject) if self.subject else None
        if reg is not None and reg.schema_type == "PROTOBUF":
            codec = pb.codec_for_text(
                str(reg.schema),
                tuple(str(r) for r in reg.references if r),
                self.full_name,
            )
            # frame with the root's index among the schema's declared
            # messages — a root that is not the first top-level message
            # must not be framed as ([0]) or registry-faithful consumers
            # decode the wrong type
            indexes = pb.message_index_path(str(reg.schema), codec.root)
            self._writer_cache = (reg.schema_id, codec, indexes)
        else:
            text, messages = pb.sql_to_proto_schema(
                columns, nullable_all=self.nullable_all
            )
            sid = self.registry.register(
                self.subject or "anonymous-value", "PROTOBUF", text
            )
            self._writer_cache = (
                sid, pb.ProtoCodec(messages, "ConnectDefault1"), (0,)
            )
        return self._writer_cache

    def serialize(self, row, columns):
        if row is None:
            return None
        if self.registry is not None:
            from ksql_tpu.serde import proto_binary as pb

            sid, codec, indexes = self._writer_codec(columns)
            value = {c.name: row.get(c.name) for c in columns}
            if not self.nullable_all:
                value = {
                    c.name: _proto3_default(value.get(c.name), c.type)
                    for c in columns
                }
            return pb.frame(sid, codec.encode(value), indexes)
        if not self.nullable_all:
            row = {c.name: _proto3_default(row.get(c.name), c.type) for c in columns}
        return super().serialize(row, columns)

    def deserialize(self, payload, columns):
        from ksql_tpu.serde import proto_binary as pb

        if self.registry is not None and pb.is_framed(payload):
            sid, _indexes, body = pb.unframe(bytes(payload))
            if self._reader_cache is not None and self._reader_cache[0] == sid:
                codec = self._reader_cache[1]
            else:
                reg = self.registry.get_by_id(sid)
                if reg is None:
                    raise SerdeException(f"unknown schema id {sid}")
                codec = pb.codec_for_text(
                    str(reg.schema),
                    tuple(str(r) for r in reg.references if r),
                    self.full_name,
                )
                self._reader_cache = (sid, codec)
            obj = codec.decode(bytes(body))
            upper = {k.upper(): v for k, v in obj.items()}
            out = {c.name: _coerce(upper.get(c.name.upper()), c.type) for c in columns}
            if not self.nullable_all:
                out = {c.name: _proto3_default(out.get(c.name), c.type) for c in columns}
            return self._f32(out)
        out = super().deserialize(payload, columns)
        if out is None:
            return None
        if not self.nullable_all:
            out = {c.name: _proto3_default(out.get(c.name), c.type) for c in columns}
        return self._f32(out)


class ProtobufNoSRFormat(ProtobufFormat):
    """PROTOBUF_NOSR: raw proto3 wire bytes with NO registry and NO framing;
    both sides derive the message from the SQL schema
    (serde/protobuf/ProtobufNoSRFormat.java:29 — the schema travels in the
    query plan, not in SR).  ``binary=True`` selects the wire tier; the
    default stays on the logical JSON tier the in-process topics use."""

    name = "PROTOBUF_NOSR"

    def __init__(self, wrap: bool = True, nullable_all: bool = False,
                 float32: tuple = (), binary: bool = False):
        super().__init__(wrap, nullable_all, float32)
        self.binary = binary
        self._codec_cache: Dict[Any, Any] = {}

    def _codec(self, columns):
        from ksql_tpu.serde import proto_binary as pb

        key = tuple((c.name, str(c.type)) for c in columns)
        codec = self._codec_cache.get(key)
        if codec is None:
            _text, messages = pb.sql_to_proto_schema(
                columns, nullable_all=self.nullable_all
            )
            codec = pb.ProtoCodec(messages, "ConnectDefault1")
            self._codec_cache[key] = codec
        return codec

    def serialize(self, row, columns):
        if row is None:
            return None
        if not self.binary:
            return super().serialize(row, columns)
        value = {c.name: row.get(c.name) for c in columns}
        if not self.nullable_all:
            value = {
                c.name: _proto3_default(value.get(c.name), c.type)
                for c in columns
            }
        return self._codec(columns).encode(value)

    def deserialize(self, payload, columns):
        if self.binary and isinstance(payload, (bytes, bytearray)):
            obj = self._codec(columns).decode(bytes(payload))
            upper = {k.upper(): v for k, v in obj.items()}
            out = {c.name: _coerce(upper.get(c.name.upper()), c.type) for c in columns}
            if not self.nullable_all:
                out = {c.name: _proto3_default(out.get(c.name), c.type) for c in columns}
            return self._f32(out)
        return super().deserialize(payload, columns)


class NoneFormat(Format):
    name = "NONE"

    def serialize(self, row, columns):
        return None

    def deserialize(self, payload, columns):
        return {}


_FORMATS: Dict[str, Any] = {
    "JSON": JsonFormat,
    "JSON_SR": JsonFormat,  # schema'd JSON (SR integration pending)
    "AVRO": AvroFormat,
    "PROTOBUF": ProtobufFormat,
    "PROTOBUF_NOSR": ProtobufNoSRFormat,
    "DELIMITED": DelimitedFormat,
    "KAFKA": KafkaFormat,
    "NONE": NoneFormat,
}


# SerdeFeature support per format (each Format's supportedFeatures:
# json/JsonFormat.java:34, avro/AvroFormat.java:36,
# protobuf/ProtobufFormat.java:35 — PROTOBUF-with-SR is wrap-only)
WRAPPABLE = {"JSON", "JSON_SR", "AVRO", "PROTOBUF", "PROTOBUF_NOSR"}
# WRAP_SINGLE_VALUE=false is also accepted by formats that are inherently
# unwrapped (KAFKA, DELIMITED, NONE): it merely states the status quo
# (SerdeFeaturesFactory) — only =true errors there
UNWRAPPABLE_VALUES = {"JSON", "JSON_SR", "AVRO", "PROTOBUF_NOSR", "KAFKA",
                      "DELIMITED", "NONE"}
# formats where single KEY columns serialize unwrapped
UNWRAPPABLE = {"JSON", "JSON_SR", "AVRO", "PROTOBUF_NOSR", "DELIMITED", "KAFKA", "NONE"}


class _FaultingFormat(Format):
    """Serde-seam fault proxy (wrapped around every ``of()`` result): fires
    the ``serde.serialize`` / ``serde.deserialize`` fault points with the
    format name as context, then delegates.  Corrupt-mode rules mangle the
    payload *before* the real serde sees it, so corruption surfaces as the
    format's own SerdeException."""

    def __init__(self, inner: Format):
        self._inner = inner
        self.name = inner.name

    def serialize(self, row, columns):
        payload = self._inner.serialize(row, columns)
        return faults.fault_point("serde.serialize", self.name, payload)

    def deserialize(self, payload, columns):
        payload = faults.fault_point("serde.deserialize", self.name, payload)
        return self._inner.deserialize(payload, columns)

    def __getattr__(self, attr):  # format-specific surface (wrap, schema, ...)
        return getattr(self._inner, attr)


def of(
    name: str,
    properties: Optional[Dict[str, Any]] = None,
    wrap_single_values: Optional[bool] = None,
    registry=None,
    subject: Optional[str] = None,
) -> Format:
    """FormatFactory.of analog.  Passing a schema ``registry`` (+``subject``)
    to a registry-backed format selects its binary wire tier.  With fault
    injection armed the serde is wrapped in the fault-point proxy (serdes
    are cached per step, so arm faults before queries start)."""
    serde = _of(name, properties, wrap_single_values, registry, subject)
    if faults.armed():
        return _FaultingFormat(serde)
    return serde


def _of(
    name: str,
    properties: Optional[Dict[str, Any]] = None,
    wrap_single_values: Optional[bool] = None,
    registry=None,
    subject: Optional[str] = None,
) -> Format:
    cls = _FORMATS.get(name.upper())
    if cls is None:
        raise SerdeException(f"Unknown format: {name}")
    if cls is DelimitedFormat:
        delim = (properties or {}).get("VALUE_DELIMITER") or ","
        named = {"SPACE": " ", "TAB": "\t"}
        return DelimitedFormat(named.get(str(delim).upper(), str(delim)))
    wrap = wrap_single_values if wrap_single_values is not None else True
    if cls is AvroFormat and registry is not None:
        return AvroFormat(wrap=wrap, registry=registry, subject=subject)
    if cls is ProtobufNoSRFormat:
        p = properties or {}
        return ProtobufNoSRFormat(
            wrap=wrap,
            nullable_all=bool(p.get("PROTO_NULLABLE_ALL", False)),
            float32=tuple(p.get("PROTO_FLOAT32", ()) or ()),
            binary=bool(p.get("PROTO_BINARY", False)),
        )
    if cls is ProtobufFormat:
        p = properties or {}
        return ProtobufFormat(
            wrap=wrap,
            nullable_all=bool(p.get("PROTO_NULLABLE_ALL", False)),
            float32=tuple(p.get("PROTO_FLOAT32", ()) or ()),
            registry=registry,
            subject=subject,
            full_name=p.get("PROTO_FULL_NAME"),
        )
    if issubclass(cls, JsonFormat) and wrap_single_values is not None:
        return cls(wrap=wrap_single_values)
    return cls()


def serialize_key(key_format: str, key: Tuple[Any, ...], key_columns,
                  wrapped: bool = False, delimiter: Optional[str] = None) -> Any:
    """Serialize a key tuple to its on-topic representation.

    Single key columns are unwrapped for every format that supports it
    (SerdeFeaturesFactory.buildKeyFeatures); PROTOBUF stays wrapped.
    DELIMITED keys are CSV text; envelope formats with multiple key columns
    produce a column-name-keyed object."""
    cols = list(key_columns)
    if not cols:
        return None
    if not key:
        # source record key payload was null and passed through untouched
        # (Kafka Streams forwards the original null key bytes)
        return None
    kf = key_format.upper()
    if kf == "DELIMITED":
        if all(v is None for v in key):
            return None
        named = {"SPACE": " ", "TAB": "\t"}
        d = named.get(str(delimiter).upper(), delimiter) if delimiter else ","
        return DelimitedFormat(d).serialize(
            {c.name: v for c, v in zip(cols, key)}, cols
        )
    if len(cols) == 1 and kf != "PROTOBUF" and not wrapped:
        return key[0]
    if kf in ("PROTOBUF", "PROTOBUF_NOSR"):
        if all(v is None for v in key):
            return None  # null key message
        return {c.name: _proto3_default(v, c.type) for c, v in zip(cols, key)}
    return {c.name: v for c, v in zip(cols, key)}


def deserialize_key(key_format: str, payload: Any, key_columns,
                    delimiter: Optional[str] = None) -> Dict[str, Any]:
    """Inverse of serialize_key: on-topic key -> column dict."""
    cols = list(key_columns)
    if not cols or payload is None:
        return {}
    kf = key_format.upper()
    if isinstance(payload, tuple):
        return {c.name: v for c, v in zip(cols, payload)}
    if isinstance(payload, dict):
        upper = {k.upper(): v for k, v in payload.items()}
        if (
            len(cols) == 1
            and cols[0].type.base == SqlBaseType.STRUCT
            and cols[0].name.upper() not in upper
        ):
            # unwrapped single struct key: the payload IS the struct value
            return {cols[0].name: _coerce(payload, cols[0].type)}
        out = {c.name: _coerce(upper.get(c.name.upper()), c.type) for c in cols}
        if kf in ("PROTOBUF", "PROTOBUF_NOSR"):
            out = {c.name: _proto3_default(out.get(c.name), c.type) for c in cols}
        return out
    if kf == "DELIMITED":
        named = {"SPACE": " ", "TAB": "\t"}
        d = named.get(str(delimiter).upper(), delimiter) if delimiter else ","
        return DelimitedFormat(d).deserialize(payload, cols) or {}
    if len(cols) == 1:
        return {cols[0].name: _coerce(payload, cols[0].type)}
    raise SerdeException(f"cannot deserialize key {payload!r} into {len(cols)} columns")


def supported_formats() -> List[str]:
    return sorted(_FORMATS)


_DELIMITED_TYPES = {
    SqlBaseType.BOOLEAN, SqlBaseType.INTEGER, SqlBaseType.BIGINT,
    SqlBaseType.DOUBLE, SqlBaseType.DECIMAL, SqlBaseType.STRING,
    SqlBaseType.BYTES, SqlBaseType.TIME, SqlBaseType.DATE, SqlBaseType.TIMESTAMP,
}
_KAFKA_TYPES = {
    SqlBaseType.INTEGER, SqlBaseType.BIGINT, SqlBaseType.DOUBLE,
    SqlBaseType.STRING, SqlBaseType.BYTES,
}


AVRO_NAME = __import__("re").compile(r"[A-Za-z_][A-Za-z0-9_]*$")


def _check_map_keys(t: SqlType, fmt: str) -> None:
    if t.base == SqlBaseType.MAP and t.key is not None and t.key.base != SqlBaseType.STRING:
        raise SerdeException(f"{fmt} only supports MAPs with STRING keys")
    for sub in (t.element, t.key):
        if sub is not None:
            _check_map_keys(sub, fmt)
    for _n, ft in t.fields or ():
        _check_map_keys(ft, fmt)


def _check_avro_names(name: str, t: SqlType) -> None:
    if not AVRO_NAME.match(name):
        raise SerdeException(
            f"Schema is not compatible with Avro: Illegal initial character: {name}"
        )
    for fn_, ft in t.fields or ():
        _check_avro_names(fn_, ft)
    if t.element is not None:
        for fn_, ft in t.element.fields or ():
            _check_avro_names(fn_, ft)


def check_schema_support(format_name: str, columns, what: str) -> None:
    """Validate a format can (de)serialize the given columns (the reference's
    Format.supportedFeatures/schema validation, e.g. DelimitedFormat rejects
    nested types and KafkaFormat is single-primitive-only)."""
    f = format_name.upper()
    cols = list(columns)
    if f in ("AVRO", "JSON", "JSON_SR", "PROTOBUF", "PROTOBUF_NOSR"):
        nice = "Avro" if f == "AVRO" else f
        for c in cols:
            _check_map_keys(c.type, nice)
    if f == "AVRO":
        for c in cols:
            _check_avro_names(c.name, c.type)
    if f == "DELIMITED":
        for c in cols:
            if c.type.base not in _DELIMITED_TYPES:
                raise SerdeException(
                    f"The 'DELIMITED' format does not support type '{c.type.base.value}', "
                    f"column: `{c.name}`"
                )
    if f == "KAFKA":
        if len(cols) > 1:
            schema_desc = ", ".join(f"`{c.name}` {c.type} KEY" for c in cols)
            raise SerdeException(
                ("Key format does not support schema.\nformat: KAFKA\n"
                 f"schema: Persistence{{columns=[{schema_desc}], features=[]}}\n"
                 "reason: The 'KAFKA' format only supports a single field. Got: "
                 if what == "key" else
                 "The 'KAFKA' format only supports a single field. Got: ")
                + str([f"`{c.name}` {c.type}" for c in cols])
            )
        for c in cols:
            if c.type.base not in _KAFKA_TYPES:
                raise SerdeException(
                    f"The 'KAFKA' format does not support type '{c.type.base.value}', "
                    f"column: `{c.name}`"
                )
    if f == "NONE" and what == "value" and cols:
        raise SerdeException(
            "The 'NONE' format can only be used when no columns are defined."
        )


def contains_map(t: SqlType) -> bool:
    if t.base == SqlBaseType.MAP:
        return True
    if t.element is not None and contains_map(t.element):
        return True
    if t.key is not None and contains_map(t.key):
        return True
    for _, ft in t.fields or ():
        if contains_map(ft):
            return True
    return False
