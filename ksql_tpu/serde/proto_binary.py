"""Binary protobuf wire codec + Confluent framing.

Byte-level implementation of the proto3 wire format (varints, 64/32-bit
fixed, length-delimited; packed repeated scalars; map entry messages) over
the descriptor IR that ``schema_registry._parse_proto`` produces — no
generated code, no protobuf runtime.  The reference does this work through
Connect's ProtobufData + Confluent ProtobufConverter
(ksqldb-serde/src/main/java/io/confluent/ksql/serde/protobuf/
ProtobufFormat.java:31, ProtobufSerdeFactory.java, ProtobufSchemaTranslator
.java); this module is the from-scratch equivalent, wired to the in-process
schema registry through the Confluent protobuf framing:
[magic 0x00][schema id, 4-byte BE][message-index path][wire bytes]
(the index path for the first top-level message is the single byte 0x00).

Well-known message types map to SQL host representations the way Connect
data does: google.protobuf.Timestamp <-> epoch-millis BIGINT host value,
google.type.Date <-> epoch-days, google.type.TimeOfDay <-> millis-of-day,
confluent.type.Decimal <-> decimal.Decimal, wrapper types <-> nullable
scalars.
"""

from __future__ import annotations

import datetime
import decimal
import io
import struct
from typing import Any, Dict, List, Optional, Tuple

from ksql_tpu.common.errors import SerdeException
from ksql_tpu.common.types import SqlBaseType, SqlType
from ksql_tpu.serde.schema_registry import (
    _parse_proto,
    _ProtoField,
    _ProtoMessage,
)

MAGIC = b"\x00"

# wire types
WT_VARINT, WT_I64, WT_LEN, WT_I32 = 0, 1, 2, 5

_VARINT_TYPES = {"int32", "int64", "uint32", "uint64", "sint32", "sint64", "bool"}
_I64_TYPES = {"fixed64", "sfixed64", "double"}
_I32_TYPES = {"fixed32", "sfixed32", "float"}
_SCALARS = _VARINT_TYPES | _I64_TYPES | _I32_TYPES | {"string", "bytes"}

#: full names of well-known message types the codec converts in/out of SQL
#: host representations (everything else message-typed is a STRUCT dict)
WK_TIMESTAMP = "google.protobuf.Timestamp"
WK_DATE = "google.type.Date"
WK_TIME = "google.type.TimeOfDay"
WK_DECIMAL = "confluent.type.Decimal"
_WRAPPERS = {
    "google.protobuf.BoolValue": "bool",
    "google.protobuf.Int32Value": "int32",
    "google.protobuf.UInt32Value": "uint32",
    "google.protobuf.Int64Value": "int64",
    "google.protobuf.UInt64Value": "uint64",
    "google.protobuf.FloatValue": "float",
    "google.protobuf.DoubleValue": "double",
    "google.protobuf.StringValue": "string",
    "google.protobuf.BytesValue": "bytes",
}
_WELL_KNOWN_MESSAGES = {WK_TIMESTAMP, WK_DATE, WK_TIME, WK_DECIMAL} | set(_WRAPPERS)

_EPOCH = datetime.date(1970, 1, 1)


# ----------------------------------------------------------- primitive io


def write_varint(out: io.BytesIO, v: int) -> None:
    """Unsigned base-128 varint; negatives encode as 64-bit two's complement
    (proto3 int32/int64 semantics)."""
    v &= (1 << 64) - 1
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def read_varint(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        raw = buf.read(1)
        if not raw:
            raise SerdeException("truncated protobuf varint")
        b = raw[0]
        acc |= (b & 0x7F) << shift
        if not b & 0x80:
            return acc
        shift += 7
        if shift > 63:
            raise SerdeException("protobuf varint too long")


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _unzigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _signed32(v: int) -> int:
    v &= (1 << 32) - 1
    return v - (1 << 32) if v >= (1 << 31) else v


def write_tag(out: io.BytesIO, number: int, wt: int) -> None:
    write_varint(out, (number << 3) | wt)


def _wire_type_of(type_name: str) -> int:
    if type_name in _VARINT_TYPES:
        return WT_VARINT
    if type_name in _I64_TYPES:
        return WT_I64
    if type_name in _I32_TYPES:
        return WT_I32
    return WT_LEN  # string/bytes/message/map/packed


# ------------------------------------------------------------------ encode


def _write_scalar(out: io.BytesIO, type_name: str, v: Any) -> None:
    if type_name == "bool":
        write_varint(out, 1 if v else 0)
    elif type_name in ("int32", "int64", "uint32", "uint64"):
        write_varint(out, int(v))
    elif type_name in ("sint32", "sint64"):
        write_varint(out, _zigzag(int(v)))
    elif type_name == "double":
        out.write(struct.pack("<d", float(v)))
    elif type_name == "fixed64":
        out.write(struct.pack("<Q", int(v) & ((1 << 64) - 1)))
    elif type_name == "sfixed64":
        out.write(struct.pack("<q", int(v)))
    elif type_name == "float":
        out.write(struct.pack("<f", float(v)))
    elif type_name == "fixed32":
        out.write(struct.pack("<I", int(v) & ((1 << 32) - 1)))
    elif type_name == "sfixed32":
        out.write(struct.pack("<i", int(v)))
    elif type_name == "string":
        data = str(v).encode("utf-8")
        write_varint(out, len(data))
        out.write(data)
    elif type_name == "bytes":
        data = bytes(v)
        write_varint(out, len(data))
        out.write(data)
    else:
        raise SerdeException(f"not a protobuf scalar: {type_name}")


def _scalar_default(type_name: str) -> Any:
    if type_name == "bool":
        return False
    if type_name == "string":
        return ""
    if type_name == "bytes":
        return b""
    if type_name in ("double", "float"):
        return 0.0
    return 0


def _well_known_payload(full_name: str, v: Any) -> Dict[int, Tuple[str, Any]]:
    """Host value -> {field number: (scalar type, value)} for a well-known."""
    if full_name == WK_TIMESTAMP:
        ms = int(v)
        sec, rem = divmod(ms, 1000)
        return {1: ("int64", sec), 2: ("int32", rem * 1_000_000)}
    if full_name == WK_DATE:
        d = _EPOCH + datetime.timedelta(days=int(v))
        return {1: ("int32", d.year), 2: ("int32", d.month), 3: ("int32", d.day)}
    if full_name == WK_TIME:
        ms = int(v)
        h, rem = divmod(ms, 3_600_000)
        mnt, rem = divmod(rem, 60_000)
        s, ms_rem = divmod(rem, 1000)
        return {
            1: ("int32", h), 2: ("int32", mnt),
            3: ("int32", s), 4: ("int32", ms_rem * 1_000_000),
        }
    if full_name == WK_DECIMAL:
        d = v if isinstance(v, decimal.Decimal) else decimal.Decimal(str(v))
        scale = -d.as_tuple().exponent if d.as_tuple().exponent < 0 else 0
        unscaled = int(d.scaleb(scale))
        nbytes = max(1, (unscaled.bit_length() + 8) // 8)
        return {
            1: ("bytes", unscaled.to_bytes(nbytes, "big", signed=True)),
            3: ("int32", scale),
        }
    wrapped = _WRAPPERS.get(full_name)
    if wrapped is not None:
        return {1: (wrapped, v)}
    raise SerdeException(f"unknown well-known type {full_name}")


class ProtoCodec:
    """Encoder/decoder over a parsed message set.

    ``messages`` maps full names to ``_ProtoMessage``; ``root`` names the
    message a payload en/decodes as.  Type-name resolution follows the
    parser's scoping (innermost scope outward)."""

    def __init__(self, messages: Dict[str, _ProtoMessage], root: str):
        self.messages = messages
        if root not in messages:
            raise SerdeException(f"unknown root message {root!r}")
        self.root = root

    # -- resolution

    def _resolve(self, type_name: str, scope: str) -> Optional[_ProtoMessage]:
        if type_name in _SCALARS:
            return None
        if scope:
            parts = scope.split(".")
            for k in range(len(parts), 0, -1):
                m = self.messages.get(".".join(parts[:k]) + "." + type_name)
                if m is not None:
                    return m
        m = self.messages.get(type_name)
        if m is None and type_name not in _WELL_KNOWN_MESSAGES:
            raise SerdeException(f"unknown protobuf type {type_name}")
        return m

    def _is_enum(self, msg: Optional[_ProtoMessage]) -> bool:
        return msg is not None and bool(msg.fields) and msg.fields[0].name == "__enum__"

    # -- encode

    def encode(self, value: Dict[str, Any]) -> bytes:
        out = io.BytesIO()
        self._encode_msg(out, self.messages[self.root], value or {})
        return out.getvalue()

    def _encode_msg(self, out: io.BytesIO, msg: _ProtoMessage, value: Dict[str, Any]) -> None:
        lookup = {str(k).upper(): v for k, v in (value or {}).items()}
        for f in msg.fields:
            if f.name == "__enum__":
                continue
            v = lookup.get(f.name.upper())
            self._encode_field(out, msg, f, v)

    def _encode_field(self, out: io.BytesIO, msg: _ProtoMessage, f: _ProtoField, v: Any) -> None:
        if f.map_kv is not None:
            for mk, mv in (v or {}).items():
                entry = io.BytesIO()
                ktype, vtype_name = f.map_kv
                if mk is not None:
                    kcast = (mk if ktype == "string" else
                             (bool(mk) if ktype == "bool" else int(mk)))
                    if kcast != _scalar_default(ktype):
                        write_tag(entry, 1, _wire_type_of(ktype))
                        _write_scalar(entry, ktype, kcast)
                self._encode_single(entry, msg, vtype_name, 2, mv, optional=False)
                data = entry.getvalue()
                write_tag(out, f.number, WT_LEN)
                write_varint(out, len(data))
                out.write(data)
            return
        if f.repeated:
            seq = list(v) if v is not None else []
            if not seq:
                return
            if f.type_name in _VARINT_TYPES | _I64_TYPES | _I32_TYPES:
                packed = io.BytesIO()  # proto3 default: packed numerics
                for item in seq:
                    _write_scalar(packed, f.type_name, item)
                data = packed.getvalue()
                write_tag(out, f.number, WT_LEN)
                write_varint(out, len(data))
                out.write(data)
            else:
                for item in seq:
                    self._encode_single(out, msg, f.type_name, f.number, item,
                                        optional=True)
            return
        self._encode_single(out, msg, f.type_name, f.number, v, f.optional)

    def _encode_single(self, out: io.BytesIO, msg: _ProtoMessage,
                       type_name: str, number: int, v: Any, optional: bool) -> None:
        sub = self._resolve(type_name, msg.name)
        if self._is_enum(sub):
            return  # enum values unsupported as data: emit default
        if type_name in _SCALARS:
            if v is None:
                return
            # proto3: default-valued non-optional scalars are not emitted
            if not optional and v == _scalar_default(type_name):
                if not (type_name == "bool" and v is True):
                    return
            write_tag(out, number, _wire_type_of(type_name))
            _write_scalar(out, type_name, v)
            return
        if v is None:
            return  # absent message field
        body = io.BytesIO()
        if sub is None:  # well-known
            for num, (st, sv) in _well_known_payload(type_name, v).items():
                if sv is None or sv == _scalar_default(st):
                    continue  # proto3 drops defaults; message presence = non-null
                write_tag(body, num, _wire_type_of(st))
                _write_scalar(body, st, sv)
        else:
            if not isinstance(v, dict):
                raise SerdeException(
                    f"expected dict for message {type_name}, got {type(v).__name__}"
                )
            self._encode_msg(body, sub, v)
        data = body.getvalue()
        write_tag(out, number, WT_LEN)
        write_varint(out, len(data))
        out.write(data)

    # -- decode

    def decode(self, payload: bytes) -> Dict[str, Any]:
        return self._decode_msg(self.messages[self.root], payload)

    def _read_raw_fields(self, payload: bytes) -> List[Tuple[int, int, Any]]:
        buf = io.BytesIO(payload)
        out = []
        while True:
            start = buf.tell()
            if start >= len(payload):
                break
            tag = read_varint(buf)
            number, wt = tag >> 3, tag & 7
            if wt == WT_VARINT:
                out.append((number, wt, read_varint(buf)))
            elif wt == WT_I64:
                out.append((number, wt, buf.read(8)))
            elif wt == WT_I32:
                out.append((number, wt, buf.read(4)))
            elif wt == WT_LEN:
                n = read_varint(buf)
                data = buf.read(n)
                if len(data) != n:
                    raise SerdeException("truncated length-delimited field")
                out.append((number, wt, data))
            else:
                raise SerdeException(f"unsupported wire type {wt}")
        return out

    def _decode_scalar(self, type_name: str, wt: int, raw: Any) -> Any:
        if type_name == "bool":
            return bool(raw)
        if type_name in ("int32", "int64"):
            return _signed64(int(raw))
        if type_name in ("uint32", "uint64"):
            return int(raw)
        if type_name in ("sint32", "sint64"):
            return _unzigzag(int(raw))
        if type_name == "double":
            return struct.unpack("<d", raw)[0]
        if type_name == "float":
            return struct.unpack("<f", raw)[0]
        if type_name == "fixed64":
            return struct.unpack("<Q", raw)[0]
        if type_name == "sfixed64":
            return struct.unpack("<q", raw)[0]
        if type_name == "fixed32":
            return struct.unpack("<I", raw)[0]
        if type_name == "sfixed32":
            return struct.unpack("<i", raw)[0]
        if type_name == "string":
            return raw.decode("utf-8")
        if type_name == "bytes":
            return bytes(raw)
        raise SerdeException(f"not a protobuf scalar: {type_name}")

    def _unpack_repeated(self, type_name: str, wt: int, raw: Any) -> List[Any]:
        if wt == WT_LEN and type_name in _VARINT_TYPES | _I64_TYPES | _I32_TYPES:
            buf = io.BytesIO(raw)
            out = []
            while buf.tell() < len(raw):
                if type_name in _VARINT_TYPES:
                    out.append(self._decode_scalar(type_name, WT_VARINT, read_varint(buf)))
                elif type_name in _I64_TYPES:
                    out.append(self._decode_scalar(type_name, WT_I64, buf.read(8)))
                else:
                    out.append(self._decode_scalar(type_name, WT_I32, buf.read(4)))
            return out
        return [self._decode_scalar(type_name, wt, raw)]

    def _decode_well_known(self, full_name: str, payload: bytes) -> Any:
        fields = {num: raw for num, _wt, raw in self._read_raw_fields(payload)}

        def geti(num: int) -> int:
            raw = fields.get(num, 0)
            return _signed64(int(raw)) if isinstance(raw, int) else 0

        if full_name == WK_TIMESTAMP:
            return geti(1) * 1000 + geti(2) // 1_000_000
        if full_name == WK_DATE:
            y, m, d = geti(1) or 1970, geti(2) or 1, geti(3) or 1
            return (datetime.date(y, m, d) - _EPOCH).days
        if full_name == WK_TIME:
            return (geti(1) * 3_600_000 + geti(2) * 60_000 + geti(3) * 1000
                    + geti(4) // 1_000_000)
        if full_name == WK_DECIMAL:
            data = fields.get(1, b"")
            unscaled = int.from_bytes(data, "big", signed=True) if data else 0
            return decimal.Decimal(unscaled).scaleb(-geti(3))
        wrapped = _WRAPPERS.get(full_name)
        if wrapped is not None:
            raw = fields.get(1)
            if raw is None:
                return self._decode_scalar(wrapped, _wire_type_of(wrapped),
                                           b"\0" * 8) if wrapped in _I64_TYPES else (
                    self._decode_scalar(wrapped, _wire_type_of(wrapped), b"\0" * 4)
                    if wrapped in _I32_TYPES else _scalar_default(wrapped))
            return self._decode_scalar(wrapped, _wire_type_of(wrapped), raw)
        raise SerdeException(f"unknown well-known type {full_name}")

    def _decode_msg(self, msg: _ProtoMessage, payload: bytes) -> Dict[str, Any]:
        raw_fields = self._read_raw_fields(payload)
        by_number: Dict[int, List[Tuple[int, Any]]] = {}
        for num, wt, raw in raw_fields:
            by_number.setdefault(num, []).append((wt, raw))
        out: Dict[str, Any] = {}
        for f in msg.fields:
            if f.name == "__enum__":
                continue
            got = by_number.get(f.number)
            if f.map_kv is not None:
                ktype, vtype_name = f.map_kv
                m: Dict[Any, Any] = {}
                for wt, raw in got or ():
                    entries = self._read_raw_fields(raw)
                    kv = {num: (w, r) for num, w, r in entries}
                    kraw = kv.get(1)
                    k = (self._decode_scalar(ktype, *kraw) if kraw
                         else _scalar_default(ktype))
                    vraw = kv.get(2)
                    m[k] = self._decode_value(msg, vtype_name, vraw, optional=False)
                out[f.name] = m
                continue
            if f.repeated:
                items: List[Any] = []
                sub = self._resolve(f.type_name, msg.name)
                for wt, raw in got or ():
                    if f.type_name in _SCALARS:
                        items.extend(self._unpack_repeated(f.type_name, wt, raw))
                    elif self._is_enum(sub):
                        items.append(None)
                    elif sub is None:
                        items.append(self._decode_well_known(f.type_name, raw))
                    else:
                        items.append(self._decode_msg(sub, raw))
                out[f.name] = items
                continue
            last = got[-1] if got else None
            out[f.name] = self._decode_value(msg, f.type_name, last, f.optional)
        return out

    def _decode_value(self, msg: _ProtoMessage, type_name: str,
                      wt_raw: Optional[Tuple[int, Any]], optional: bool) -> Any:
        sub = self._resolve(type_name, msg.name)
        if self._is_enum(sub):
            return None
        if type_name in _SCALARS:
            if wt_raw is None:
                return None if optional else _scalar_default(type_name)
            return self._decode_scalar(type_name, *wt_raw)
        if wt_raw is None:
            return None  # absent message field is null
        if sub is None:
            return self._decode_well_known(type_name, wt_raw[1])
        return self._decode_msg(sub, wt_raw[1])


# --------------------------------------------------- Confluent wire framing


def frame(schema_id: int, payload: bytes, indexes: Tuple[int, ...] = (0,)) -> bytes:
    """[0x00][schema id BE][message-index path][payload].  The index path
    ints are ZIGZAG varints (Kafka ByteUtils.writeVarint, which Confluent's
    MessageIndexes uses); the path for the first top-level message ([0]) is
    the optimized single byte 0x00."""
    out = io.BytesIO()
    out.write(MAGIC)
    out.write(struct.pack(">I", schema_id))
    if tuple(indexes) == (0,):
        out.write(b"\x00")
    else:
        write_varint(out, _zigzag(len(indexes)))
        for i in indexes:
            write_varint(out, _zigzag(i))
    out.write(payload)
    return out.getvalue()


def unframe(data: bytes) -> Tuple[int, Tuple[int, ...], bytes]:
    if len(data) < 6 or data[:1] != MAGIC:
        raise SerdeException("payload is not Confluent-framed protobuf")
    sid = struct.unpack(">I", data[1:5])[0]
    buf = io.BytesIO(data[5:])
    n = _unzigzag(read_varint(buf))
    indexes = tuple(_unzigzag(read_varint(buf)) for _ in range(n)) if n else (0,)
    return sid, indexes, buf.read()


def is_framed(data: Any) -> bool:
    return isinstance(data, (bytes, bytearray)) and len(data) >= 6 and data[:1] == MAGIC


def message_index_path(text: str, root: str) -> Tuple[int, ...]:
    """Confluent MessageIndexes path of ``root`` within a schema text: the
    message's index among its siblings at each nesting level, declaration
    order, messages only (enums are not counted — they live in a separate
    index space, matching ProtobufSchema.toMessageIndexes).  Returns (0,)
    when ``root`` is not declared in ``text`` (e.g. it resolved out of a
    schema reference, whose payloads the first-message default covers)."""
    main = _parse_proto(text)

    def is_enum(m) -> bool:
        return bool(m.fields) and m.fields[0].name == "__enum__"

    parts = str(root).split(".")
    path = []
    for depth in range(1, len(parts) + 1):
        name = ".".join(parts[:depth])
        parent = ".".join(parts[: depth - 1])
        siblings = [
            n for n, m in main.items()
            if not is_enum(m)
            and (n.rsplit(".", 1)[0] if "." in n else "") == parent
        ]
        if name not in siblings:
            return (0,)
        path.append(siblings.index(name))
    return tuple(path)


# ------------------------------------------------------- SQL schema bridge


def sql_to_proto_schema(
    columns, name: str = "ConnectDefault1", nullable_all: bool = False,
) -> Tuple[str, Dict[str, _ProtoMessage]]:
    """Build (proto text, parsed message set) from SQL value columns — the
    ProtobufSchemaTranslator/ProtobufData analog.  Field numbers are
    sequential in declaration order, as Connect assigns them.  With
    ``nullable_all`` scalar columns use wrapper types
    (VALUE_PROTOBUF_NULLABLE_REPRESENTATION=WRAPPER)."""
    nested_count = [0]

    def scalar_of(t: SqlType) -> Optional[str]:
        return {
            SqlBaseType.BOOLEAN: "bool",
            SqlBaseType.INTEGER: "int32",
            SqlBaseType.BIGINT: "int64",
            SqlBaseType.DOUBLE: "double",
            SqlBaseType.STRING: "string",
            SqlBaseType.BYTES: "bytes",
        }.get(t.base)

    _WRAPPER_OF = {
        "bool": "google.protobuf.BoolValue",
        "int32": "google.protobuf.Int32Value",
        "int64": "google.protobuf.Int64Value",
        "double": "google.protobuf.DoubleValue",
        "string": "google.protobuf.StringValue",
        "bytes": "google.protobuf.BytesValue",
    }

    def field_decl(fn: str, t: SqlType, num: int, indent: str,
                   nested: List[str], wrap_nullable: bool) -> str:
        b = t.base
        if b == SqlBaseType.ARRAY:
            et = type_name_of(t.element, indent, nested, False)
            return f"{indent}repeated {et} {fn} = {num};"
        if b == SqlBaseType.MAP:
            kt = scalar_of(t.key) if t.key is not None else "string"
            if kt not in ("int32", "int64", "bool", "string"):
                kt = "string"
            vt = type_name_of(t.element, indent, nested, False)
            return f"{indent}map<{kt}, {vt}> {fn} = {num};"
        ft = type_name_of(t, indent, nested, wrap_nullable)
        return f"{indent}{ft} {fn} = {num};"

    def type_name_of(t: SqlType, indent: str, nested: List[str],
                     wrap_nullable: bool) -> str:
        b = t.base
        s = scalar_of(t)
        if s is not None:
            return _WRAPPER_OF[s] if (wrap_nullable and nullable_all) else s
        if b == SqlBaseType.DECIMAL:
            return WK_DECIMAL
        if b == SqlBaseType.TIMESTAMP:
            return WK_TIMESTAMP
        if b == SqlBaseType.DATE:
            return WK_DATE
        if b == SqlBaseType.TIME:
            return WK_TIME
        if b == SqlBaseType.STRUCT:
            nested_count[0] += 1
            sub = f"ConnectDefault{nested_count[0] + 1}"
            sub_nested: List[str] = []
            sub_fields = [
                field_decl(fn, ft, i + 1, indent + "  ", sub_nested, True)
                for i, (fn, ft) in enumerate(t.fields or ())
            ]
            nested.append(f"{indent}message {sub} {{")
            nested.extend(sub_nested)
            nested.extend(sub_fields)
            nested.append(f"{indent}}}")
            return sub
        raise SerdeException(f"no protobuf mapping for {t}")

    nested_msgs: List[str] = []
    fields = [
        field_decl(c.name, c.type, i + 1, "  ", nested_msgs, True)
        for i, c in enumerate(columns)
    ]
    body = "\n".join(nested_msgs + fields)
    text = f'syntax = "proto3";\n\nmessage {name} {{\n{body}\n}}\n'
    return text, _parse_proto(text)


def codec_for_text(
    text: str, references: Tuple[str, ...] = (), full_name: Optional[str] = None,
) -> ProtoCodec:
    """Codec for a registered .proto schema (with SR references joined)."""
    messages: Dict[str, _ProtoMessage] = {}
    for ref in references:
        messages.update(_parse_proto(str(ref)))
    main = _parse_proto(text)
    messages.update(main)
    top = [n for n in main if "." not in n]
    if not top:
        raise SerdeException("no message in protobuf schema")
    root = top[0]
    if full_name:
        wanted = str(full_name)
        short = wanted.rsplit(".", 1)[-1]
        root = wanted if wanted in messages else (short if short in messages else root)
    return ProtoCodec(messages, root)
