"""In-process Schema Registry + schema translators.

Analog of the reference's SchemaRegistryClient integration and each SR
format's ``SchemaTranslator`` (serde/connect/ConnectFormatSchemaTranslator
.java:77, avro/AvroFormat, json/JsonSchemaFormat, protobuf/ProtobufFormat):
subjects ``<topic>-key`` / ``<topic>-value`` map to schemas, and CREATE
STREAM/TABLE statements without explicit columns infer their schema from the
registered subject (DefaultSchemaInjector analog).

Supported schema languages: AVRO (JSON schema objects), JSON (json-schema
draft-7 subset), PROTOBUF (proto3 text, single-message subset).
"""

from __future__ import annotations

import dataclasses
import json
import re
from typing import Any, Dict, List, Optional, Tuple

from ksql_tpu.common import faults
from ksql_tpu.common import types as T
from ksql_tpu.common.errors import SerdeException
from ksql_tpu.common.types import SqlType


@dataclasses.dataclass
class RegisteredSchema:
    subject: str
    schema_type: str  # AVRO | JSON | PROTOBUF
    schema: Any  # parsed JSON object or proto text
    schema_id: int = 0
    references: Tuple[Any, ...] = ()  # referenced schema texts (PROTOBUF)


class SchemaRegistry:
    """Subject -> latest schema (versioning elided: QTT only needs latest).

    Id assignment mirrors the reference's MockSchemaRegistryClient sequencing
    in the QTT harness: statement-registered schemas take ids in statement
    order, while declared topic schemas without an explicit id are *pending*
    and materialize (taking the next id) on first lookup."""

    def __init__(self) -> None:
        self._subjects: Dict[str, RegisteredSchema] = {}
        self._pending: Dict[str, Tuple[str, Any, Tuple[Any, ...]]] = {}
        self._next_id = 1

    def copy(self) -> "SchemaRegistry":
        """Fork for sandboxed validation: the sandbox may materialize
        pending subjects; id sequencing is deterministic, so the real
        execution converges to the same assignments."""
        c = SchemaRegistry()
        c._subjects = dict(self._subjects)
        c._pending = dict(self._pending)
        c._next_id = self._next_id
        return c

    def _take_id(self) -> int:
        used = {s.schema_id for s in self._subjects.values()}
        while self._next_id in used:
            self._next_id += 1
        sid = self._next_id
        self._next_id += 1
        return sid

    def register(
        self,
        subject: str,
        schema_type: str,
        schema: Any,
        references: Tuple[Any, ...] = (),
        schema_id: Optional[int] = None,
    ) -> int:
        sid = schema_id if schema_id is not None else self._take_id()
        self._subjects[subject] = RegisteredSchema(
            subject, schema_type.upper(), schema, sid, tuple(references)
        )
        self._pending.pop(subject, None)
        return sid

    def add_pending(
        self, subject: str, schema_type: str, schema: Any, references: Tuple[Any, ...] = ()
    ) -> None:
        """A declared schema with no explicit id: registered lazily on first
        lookup (so statement-order registrations take earlier ids)."""
        if subject not in self._subjects:
            self._pending[subject] = (schema_type, schema, tuple(references))

    def has_subject(self, subject: str) -> bool:
        return subject in self._subjects or subject in self._pending

    def _materialize(self, subject: str) -> None:
        if subject in self._pending:
            st, sc, refs = self._pending.pop(subject)
            self.register(subject, st, sc, refs)

    def latest(self, subject: str) -> Optional[RegisteredSchema]:
        if faults.armed():
            # a raise here models a Schema Registry outage during schema
            # inference (DefaultSchemaInjector's remote lookup)
            faults.fault_point("schema.registry.lookup", subject)
        self._materialize(subject)
        return self._subjects.get(subject)

    def get_by_id(self, sid: int) -> Optional[RegisteredSchema]:
        if faults.armed():
            faults.fault_point("schema.registry.lookup", f"id:{sid}")
        for s in self._subjects.values():
            if s.schema_id == sid:
                return s
        # Simulate the id each pending subject would take; an unknown id must
        # not permanently materialize (and renumber) pending subjects, so only
        # materialize the prefix up to the subject whose simulated id == sid —
        # and nothing at all when the simulation cannot produce sid (e.g. sid
        # falls in a gap left by an explicit-id registration).
        used = {s.schema_id for s in self._subjects.values()}
        nxt = self._next_id
        prefix: List[str] = []
        hit = False
        for subject in self._pending:
            while nxt in used:
                nxt += 1
            used.add(nxt)
            prefix.append(subject)
            if nxt == sid:
                hit = True
                break
            nxt += 1
        if not hit:
            return None
        for subject in prefix:
            self._materialize(subject)
        for s in self._subjects.values():
            if s.schema_id == sid:
                return s
        return None


# ----------------------------------------------------------- AVRO translator

_AVRO_PRIMITIVES = {
    "int": T.INTEGER,
    "long": T.BIGINT,
    "float": T.DOUBLE,
    "double": T.DOUBLE,
    "boolean": T.BOOLEAN,
    "string": T.STRING,
    "bytes": T.BYTES,
}


def avro_to_sql(schema: Any) -> SqlType:
    """Avro (parsed JSON) -> SqlType (AvroFormat's SchemaTranslator analog)."""
    if isinstance(schema, str):
        t = _AVRO_PRIMITIVES.get(schema)
        if t is None:
            raise SerdeException(f"unsupported avro type {schema!r}")
        return t
    if isinstance(schema, list):  # union: strip null
        non_null = [s for s in schema if s != "null"]
        if len(non_null) != 1:
            raise SerdeException("unsupported avro union with multiple branches")
        return avro_to_sql(non_null[0])
    if not isinstance(schema, dict):
        raise SerdeException(f"bad avro schema {schema!r}")
    t = schema.get("type")
    logical = schema.get("logicalType")
    if logical == "decimal":
        return SqlType.decimal(int(schema.get("precision", 38)), int(schema.get("scale", 0)))
    if logical == "date":
        return T.DATE
    if logical in ("time-millis", "time-micros"):
        return T.TIME
    if logical in ("timestamp-millis", "timestamp-micros"):
        return T.TIMESTAMP
    if t == "record":
        fields = [
            (f["name"].upper(), avro_to_sql(f["type"]))
            for f in schema.get("fields", ())
        ]
        return SqlType.struct(fields)
    if t == "array":
        return SqlType.array(avro_to_sql(schema["items"]))
    if t == "map":
        return SqlType.map(T.STRING, avro_to_sql(schema["values"]))
    if t == "enum":
        return T.STRING
    if t == "fixed":
        return T.BYTES
    if isinstance(t, (str, list, dict)):
        return avro_to_sql(t)
    raise SerdeException(f"unsupported avro schema {schema!r}")


def avro_columns(schema: Any) -> List[Tuple[str, SqlType]]:
    """Top-level Avro schema -> column list. Records flatten to columns;
    anonymous primitives become a single unnamed column (caller names it)."""
    if isinstance(schema, dict) and schema.get("type") == "record":
        return [
            (f["name"].upper(), avro_to_sql(f["type"]))
            for f in schema.get("fields", ())
        ]
    return [("", avro_to_sql(schema))]


# ------------------------------------------------------ JSON-schema translator

_JSONSCHEMA_PRIMITIVES = {
    "integer": T.BIGINT,
    "number": T.DOUBLE,
    "boolean": T.BOOLEAN,
    "string": T.STRING,
}


def json_schema_to_sql(schema: Any) -> SqlType:
    if isinstance(schema, bool):
        raise SerdeException("boolean json-schema unsupported")
    one_of = schema.get("oneOf") or schema.get("anyOf")
    if one_of:
        non_null = [s for s in one_of if s.get("type") != "null"]
        if len(non_null) != 1:
            raise SerdeException("unsupported json-schema union")
        return json_schema_to_sql(non_null[0])
    if schema.get("title") == "org.apache.kafka.connect.data.Decimal":
        params = schema.get("connect.parameters", {})
        return SqlType.decimal(
            int(params.get("connect.decimal.precision", 38)),
            int(params.get("scale", 0)),
        )
    t = schema.get("type")
    if isinstance(t, list):
        non_null = [x for x in t if x != "null"]
        if len(non_null) != 1:
            raise SerdeException("unsupported json-schema union")
        t = non_null[0]
    conn = schema.get("connect.type")
    if t == "integer":
        if conn in ("int8", "int16", "int32"):
            return T.INTEGER
        return T.BIGINT
    if t == "number":
        return T.INTEGER if conn in ("int8", "int16", "int32") else (
            T.BIGINT if conn == "int64" else T.DOUBLE
        )
    if t in _JSONSCHEMA_PRIMITIVES and t != "integer" and t != "number":
        return _JSONSCHEMA_PRIMITIVES[t]
    if t == "object":
        if "properties" in schema:
            fields = [
                (n.upper(), json_schema_to_sql(p))
                for n, p in schema["properties"].items()
            ]
            return SqlType.struct(fields)
        add = schema.get("additionalProperties")
        if isinstance(add, dict):
            return SqlType.map(T.STRING, json_schema_to_sql(add))
        return SqlType.map(T.STRING, T.STRING)
    if t == "array":
        return SqlType.array(json_schema_to_sql(schema.get("items", {"type": "string"})))
    raise SerdeException(f"unsupported json-schema {schema!r}")


def json_schema_columns(schema: Any) -> List[Tuple[str, SqlType]]:
    if isinstance(schema, dict) and schema.get("type") == "object" and "properties" in schema:
        return [
            (n.upper(), json_schema_to_sql(p))
            for n, p in schema["properties"].items()
        ]
    return [("", json_schema_to_sql(schema))]


# -------------------------------------------------------- PROTOBUF translator

_PROTO_PRIMITIVES = {
    "int32": T.INTEGER, "sint32": T.INTEGER, "sfixed32": T.INTEGER,
    "uint32": T.BIGINT, "fixed32": T.BIGINT,
    "int64": T.BIGINT, "sint64": T.BIGINT, "sfixed64": T.BIGINT,
    "uint64": T.BIGINT, "fixed64": T.BIGINT,
    "float": T.DOUBLE, "double": T.DOUBLE,
    "bool": T.BOOLEAN, "string": T.STRING, "bytes": T.BYTES,
}

_WELL_KNOWN = {
    "google.protobuf.Timestamp": T.TIMESTAMP,
    ".google.protobuf.Timestamp": T.TIMESTAMP,
    "google.type.Date": T.DATE,
    "google.type.TimeOfDay": T.TIME,
    "google.protobuf.Decimal": SqlType.decimal(38, 9),
    "confluent.type.Decimal": SqlType.decimal(38, 9),
    # wrapper types: message-typed, hence nullable (absent -> null)
    "google.protobuf.BoolValue": T.BOOLEAN,
    "google.protobuf.Int32Value": T.INTEGER,
    "google.protobuf.UInt32Value": T.BIGINT,
    "google.protobuf.Int64Value": T.BIGINT,
    "google.protobuf.UInt64Value": T.BIGINT,
    "google.protobuf.FloatValue": T.DOUBLE,
    "google.protobuf.DoubleValue": T.DOUBLE,
    "google.protobuf.StringValue": T.STRING,
    "google.protobuf.BytesValue": T.BYTES,
}


@dataclasses.dataclass
class _ProtoField:
    name: str
    type_name: str  # primitive name, message full/relative name, or "map"
    repeated: bool = False
    map_kv: Optional[Tuple[str, str]] = None
    number: int = 0  # wire field number (proto_binary codec)
    optional: bool = False  # explicit proto3 `optional` (or oneof branch)


@dataclasses.dataclass
class _ProtoMessage:
    name: str
    fields: List[_ProtoField]


def _parse_proto(text: str) -> Dict[str, _ProtoMessage]:
    """Minimal proto3 parser: nested messages, repeated, map<k,v>."""
    text = re.sub(r"//[^\n]*", "", text)
    text = re.sub(r"/\*.*?\*/", "", text, flags=re.S)
    messages: Dict[str, _ProtoMessage] = {}

    def parse_block(body: str, prefix: str) -> None:
        i = 0
        fields: List[_ProtoField] = []
        while i < len(body):
            m = re.match(r"\s*(message|enum)\s+(\w+)\s*\{", body[i:])
            if m:
                # find matching close brace
                depth = 0
                j = i + m.end() - 1
                while j < len(body):
                    if body[j] == "{":
                        depth += 1
                    elif body[j] == "}":
                        depth -= 1
                        if depth == 0:
                            break
                    j += 1
                inner = body[i + m.end(): j]
                sub = (prefix + "." if prefix else "") + m.group(2)
                if m.group(1) == "message":
                    parse_block(inner, sub)
                else:
                    messages[sub] = _ProtoMessage(
                        sub, [_ProtoField("__enum__", "string")]
                    )
                i = j + 1
                continue
            fm = re.match(
                r"\s*(repeated\s+|optional\s+)?map\s*<\s*(\w+)\s*,\s*([\w.]+)\s*>\s+(\w+)\s*=\s*(\d+)[^;]*;",
                body[i:],
            )
            if fm:
                fields.append(_ProtoField(
                    fm.group(4), "map", False,
                    (fm.group(2), fm.group(3)), int(fm.group(5)),
                ))
                i += fm.end()
                continue
            fm = re.match(
                r"\s*(repeated\s+|optional\s+)?([\w.]+)\s+(\w+)\s*=\s*(\d+)[^;]*;", body[i:]
            )
            if fm:
                mod = (fm.group(1) or "").strip()
                fields.append(_ProtoField(
                    fm.group(3), fm.group(2), mod == "repeated", None,
                    int(fm.group(4)), mod == "optional",
                ))
                i += fm.end()
                continue
            # skip non-field statements (syntax/package/import/option/...)
            sm = re.match(r"\s*(syntax|package|import|option|reserved)[^;]*;", body[i:])
            if sm:
                i += sm.end()
                continue
            om = re.match(r"\s*oneof\s+\w+\s*\{", body[i:])
            if om:
                # inline the oneof branches as ordinary optional fields
                i += om.end() - 1
                depth = 0
                j = i
                while j < len(body):
                    if body[j] == "{":
                        depth += 1
                    elif body[j] == "}":
                        depth -= 1
                        if depth == 0:
                            break
                    j += 1
                inner = body[i + 1: j]
                for fm2 in re.finditer(
                    r"([\w.]+)\s+(\w+)\s*=\s*(\d+)[^;]*;", inner
                ):
                    fields.append(_ProtoField(
                        fm2.group(2), fm2.group(1), False, None,
                        int(fm2.group(3)), True,
                    ))
                i = j + 1
                continue
            i += 1
        if prefix:
            messages[prefix] = _ProtoMessage(prefix, fields)

    # strip syntax/package/import lines, parse top-level messages
    parse_block(text, "")
    return messages


def _proto_field_type(
    type_name: str, messages: Dict[str, _ProtoMessage], scope: str
) -> SqlType:
    if type_name in _PROTO_PRIMITIVES:
        return _PROTO_PRIMITIVES[type_name]
    if type_name in _WELL_KNOWN:
        return _WELL_KNOWN[type_name]
    # resolve nested name relative to scope, then absolute
    candidates = []
    if scope:
        parts = scope.split(".")
        for k in range(len(parts), 0, -1):
            candidates.append(".".join(parts[:k]) + "." + type_name)
    candidates.append(type_name)
    for c in candidates:
        msg = messages.get(c)
        if msg is not None:
            if msg.fields and msg.fields[0].name == "__enum__":
                return T.STRING
            return _proto_struct(msg, messages)
    raise SerdeException(f"unknown protobuf type {type_name}")


def protobuf_float_fields(
    text: str, references: Tuple[str, ...] = (),
    full_name: Optional[str] = None,
) -> Tuple[str, ...]:
    """Top-level fields of 32-bit ``float`` type: their values round-trip
    through single precision on the wire, which the serde reproduces."""
    messages: Dict[str, _ProtoMessage] = {}
    for ref in references:
        messages.update(_parse_proto(ref))
    main = _parse_proto(text)
    messages.update(main)
    top = [m for name, m in main.items() if "." not in name]
    if not top:
        return ()
    msg = top[0]
    if full_name:
        short = str(full_name).rsplit(".", 1)[-1]
        msg = main.get(str(full_name)) or main.get(short) or msg
    return tuple(
        f.name for f in msg.fields
        if f.type_name == "float" and not f.repeated and f.map_kv is None
    )


def _proto_struct(msg: _ProtoMessage, messages: Dict[str, _ProtoMessage]) -> SqlType:
    # protobuf field names preserve case (ProtobufSchemaTranslator; QTT post
    # schemas show backticked original-case columns)
    fields = []
    for f in msg.fields:
        t = _proto_sql_of(f.type_name, f.repeated, f.map_kv, messages, msg.name)
        fields.append((f.name, t))
    return SqlType.struct(fields)


def _proto_sql_of(ftype, repeated, map_kv, messages, scope) -> SqlType:
    if map_kv is not None:
        return SqlType.map(T.STRING, _proto_field_type(map_kv[1], messages, scope))
    t = _proto_field_type(ftype, messages, scope)
    return SqlType.array(t) if repeated else t


def protobuf_columns(
    text: str, references: Tuple[str, ...] = (),
    full_name: Optional[str] = None,
) -> List[Tuple[str, SqlType]]:
    """``references``: schemas of imported .proto files (SR schema
    references) — their messages join the resolution scope.  ``full_name``
    (KEY/VALUE_SCHEMA_FULL_NAME) selects among multiple message
    definitions; the default is the first top-level message."""
    messages: Dict[str, _ProtoMessage] = {}
    for ref in references:
        messages.update(_parse_proto(ref))
    main = _parse_proto(text)
    messages.update(main)
    top = [m for name, m in main.items() if "." not in name]
    if not top:
        raise SerdeException("no message in protobuf schema")
    msg = top[0]
    if full_name:
        wanted = str(full_name)
        short = wanted.rsplit(".", 1)[-1]
        picked = main.get(wanted) or main.get(short) or messages.get(wanted)
        if picked is None:
            raise SerdeException(
                f"Schema for message {full_name} could not be found"
            )
        msg = picked
    out = []
    for f in msg.fields:
        out.append(
            (f.name, _proto_sql_of(f.type_name, f.repeated, f.map_kv, messages, msg.name))
        )
    return out


# ------------------------------------------------------------------- facade

NO_DEFAULT = object()


def columns_with_defaults(
    schema_type: str, schema: Any, references: Tuple[Any, ...] = ()
) -> List[Tuple[str, SqlType, Any]]:
    """Like columns_from_schema but with each column's write-default:
    Avro uses the field's explicit default (else NO_DEFAULT = required),
    JSON-schema properties default to null, proto3 scalars to 0/""/false."""
    st = schema_type.upper()
    if st == "KSQL":
        # engine-derived logical schema: (name, type) column list, no defaults
        return [(n, t, NO_DEFAULT) for n, t in schema]
    if st == "AVRO":
        if isinstance(schema, dict) and schema.get("type") == "record":
            out = []
            for f in schema.get("fields", ()):
                d = f["default"] if "default" in f else NO_DEFAULT
                out.append((f["name"].upper(), avro_to_sql(f["type"]), d))
            return out
        return [("", avro_to_sql(schema), NO_DEFAULT)]
    if st in ("JSON", "JSON_SR"):
        req = set(schema.get("required", ())) if isinstance(schema, dict) else set()
        return [
            (n, t, NO_DEFAULT if n in {r.upper() for r in req} else None)
            for n, t in json_schema_columns(schema)
        ]
    if st == "PROTOBUF":
        out = []
        for n, t in protobuf_columns(schema, references):
            b = t.base
            from ksql_tpu.common.types import SqlBaseType as _B

            if b in (_B.INTEGER, _B.BIGINT):
                d: Any = 0
            elif b == _B.DOUBLE:
                d = 0.0
            elif b == _B.BOOLEAN:
                d = False
            elif b == _B.STRING:
                d = ""
            elif b == _B.BYTES:
                d = b""
            elif b == _B.ARRAY:
                d = []
            elif b == _B.MAP:
                d = {}
            else:
                d = None
            out.append((n, t, d))
        return out
    raise SerdeException(f"unsupported schema type {schema_type}")


SR_FORMATS = {"AVRO", "JSON_SR", "PROTOBUF"}


def sql_type_from_schema(
    schema_type: str, schema: Any, references: Tuple[Any, ...] = (),
    full_name: Optional[str] = None,
) -> SqlType:
    """The whole physical schema as ONE SqlType (no flattening) — the
    single-column translation used for key inference (keys are always
    unwrapped: DefaultSchemaInjector buildKeyFeatures) and for
    WRAP_SINGLE_VALUE=false value inference (SerdeUtils.wrapSingle)."""
    st = schema_type.upper()
    if st == "AVRO":
        return avro_to_sql(schema)
    if st in ("JSON", "JSON_SR"):
        return json_schema_to_sql(schema)
    if st == "PROTOBUF":
        from ksql_tpu.common.types import SqlType as _T

        cols = protobuf_columns(schema, references, full_name=full_name)
        return _T.struct(list(cols))
    raise SerdeException(f"unsupported schema type {schema_type}")


def columns_from_schema(
    schema_type: str, schema: Any, references: Tuple[Any, ...] = (),
    full_name: Optional[str] = None,
) -> List[Tuple[str, SqlType]]:
    st = schema_type.upper()
    if st == "KSQL":
        # engine-derived logical schema: already a (name, type) column list
        return list(schema)
    if st == "AVRO":
        return avro_columns(schema)
    if st in ("JSON", "JSON_SR"):
        return json_schema_columns(schema)
    if st == "PROTOBUF":
        return protobuf_columns(schema, references, full_name=full_name)
    raise SerdeException(f"unsupported schema type {schema_type}")
