"""HBM-resident keyed state store — the RocksDB analog.

The reference materializes every aggregation/table in RocksDB via JNI
(ksqldb-rocksdb-config-setter/.../KsqlBoundedMemoryRocksDBConfigSetter.java:35,
Materialized stores in StreamAggregateBuilder.java).  The TPU design keeps
state *on device*: an open-addressing hash table laid out as structure-of-
arrays in HBM, updated by vectorized gather/scatter — no sort, no host
round-trip, no dynamic shapes.

Layout (all arrays length ``capacity + 1``; the last slot is the *dump slot*
that absorbs writes from inactive/overflowed lanes so every scatter has a
static target):

* ``occ``      bool      — slot occupied
* ``khash``    int64     — combined group-key hash (probe identity)
* ``wstart``   int64     — window start ms (0 when unwindowed)
* ``key<i>``   int64     — raw 64-bit repr of key column i (for emission)
* ``knull``    int32     — bitmask of NULL key columns
* ``dirty``    bool      — updated since last suppress flush (EMIT FINAL)
* ``a<j>``     per-aggregate component arrays (see device_aggs.py)

Insert algorithm (per batch, fully vectorized over rows):
repeat ``MAX_PROBES`` times — gather candidate slot; if it matches, resolve;
if empty, *claim* it by scatter-min of the row index and let the winner
write its key (losers re-examine the slot next round: if the winner had the
same key they resolve to it, otherwise they advance along the probe
sequence).  Rows still unresolved after the loop land in the dump slot and
are counted in ``overflow`` — the host reacts by growing the table
(host-side rebuild), the moral equivalent of RocksDB compaction.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

MAX_PROBES = 32

_M1 = np.array(0xBF58476D1CE4E5B9, dtype=np.uint64).view(np.int64)
_M2 = np.array(0x94D049BB133111EB, dtype=np.uint64).view(np.int64)
_GOLD = np.array(0x9E3779B97F4A7C15, dtype=np.uint64).view(np.int64)


def mix64(h: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer (logical shifts; int64 throughout)."""
    h = h ^ jax.lax.shift_right_logical(h, 30)
    h = h * _M1
    h = h ^ jax.lax.shift_right_logical(h, 27)
    h = h * _M2
    h = h ^ jax.lax.shift_right_logical(h, 31)
    return h


def combine_hash(parts: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Fold per-key-column 64-bit reprs into one group hash."""
    h = jnp.full_like(parts[0], _GOLD)
    for p in parts:
        h = mix64(h ^ p + _GOLD)
    return h


@dataclasses.dataclass(frozen=True)
class AggComponent:
    """One scatter-combined state column of an aggregate.

    ``width`` > 1 declares per-slot VECTOR state (collect/topk families):
    the store column has shape (capacity+1, width).  Vector kinds:

    * ``vec_count`` — scalar int64 count heading a collect group; the two
      following components must be ``vec_data`` (values) and ``vec_valid``
      (per-element null bits), both width-K.  ``mode`` on the vec_data
      component selects the fold: 'append' (collect_list / earliest-N,
      capped at K), 'ring' (latest-N, circular overwrite), 'set'
      (collect_set, membership-deduped append).
    * ``topk`` — self-contained width-K descending top-K of non-sentinel
      contributions; ``mode='distinct'`` dedups values (topkdistinct).
    """

    combine: str  # 'add' | 'min' | 'max' | 'argset' | 'vec_count' | 'vec_data' | 'vec_valid' | 'topk'
    dtype: str  # numpy dtype name
    init: float  # fill value for empty slots
    width: int = 1
    mode: str = ""


@dataclasses.dataclass(frozen=True)
class StoreLayout:
    capacity: int  # power of two
    num_keys: int
    components: Tuple[AggComponent, ...]
    windowed: bool = False

    def __post_init__(self):
        if self.capacity & (self.capacity - 1):
            raise ValueError("store capacity must be a power of two")


def init_store(layout: StoreLayout) -> Dict[str, jnp.ndarray]:
    c1 = layout.capacity + 1
    store = {
        "occ": jnp.zeros(c1, bool),
        # tombstoned slots: freed (evicted/deleted) but still part of probe
        # chains — linear probing must walk past them or keys inserted
        # beyond would split into duplicate slots; compaction (host rebuild
        # in _grow) reclaims them
        "grave": jnp.zeros(c1, bool),
        "khash": jnp.zeros(c1, jnp.int64),
        "wstart": jnp.zeros(c1, jnp.int64),
        "knull": jnp.zeros(c1, jnp.int32),
        "dirty": jnp.zeros(c1, bool),
        "max_ts": jnp.array(np.iinfo(np.int64).min, jnp.int64),
        "overflow": jnp.zeros((), jnp.int64),
    }
    for i in range(layout.num_keys):
        store[f"key{i}"] = jnp.zeros(c1, jnp.int64)
    for j, comp in enumerate(layout.components):
        shape = c1 if comp.width == 1 else (c1, comp.width)
        store[f"a{j}"] = jnp.full(shape, comp.init, dtype=np.dtype(comp.dtype))
    return store


def probe_insert(
    store: Dict[str, jnp.ndarray],
    capacity: int,
    khash: jnp.ndarray,
    wstart: jnp.ndarray,
    key_reprs: Sequence[jnp.ndarray],
    knull: jnp.ndarray,
    active: jnp.ndarray,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Resolve (and create) one slot per active row; returns (store, slots).

    ``slots`` is int32 per row; inactive/overflowed rows get the dump slot
    ``capacity``.
    """
    n = khash.shape[0]
    mask = capacity - 1
    dump = jnp.int32(capacity)
    rowidx = jnp.arange(n, dtype=jnp.int32)
    big = jnp.int32(n)
    base = (mix64(khash ^ (wstart * _GOLD)) & mask).astype(jnp.int32)

    def body(_, carry):
        occ, grave, kh, ws, slots, done, offset = carry
        cand = ((base + offset) & mask).astype(jnp.int32)
        c_occ = occ[cand]
        c_grave = grave[cand]
        c_used = c_occ | c_grave
        # a matching grave is reclaimed (same key re-inserted after free)
        c_match = c_used & (kh[cand] == khash) & (ws[cand] == wstart)
        newly = ~done & active & c_match
        slots = jnp.where(newly, cand, slots)
        done = done | newly
        # claim truly-empty candidates: lowest row index wins the slot.
        # Graves are NOT claimable — the key may live further down the
        # chain; compaction reclaims them.
        want = ~done & active & ~c_used
        claim = jnp.full(capacity + 1, big, jnp.int32)
        claim = claim.at[jnp.where(want, cand, dump)].min(rowidx)
        winner = want & (claim[cand] == rowidx)
        target = jnp.where(winner, cand, dump)
        occ = occ.at[target].set(True)
        occ = occ.at[capacity].set(False)
        kh = kh.at[target].set(khash)
        ws = ws.at[target].set(wstart)
        slots = jnp.where(winner, cand, slots)
        done = done | winner
        # used-by-other: advance along probe sequence; claim losers
        # re-examine the same slot next round (winner may share their key)
        offset = offset + (~done & active & c_used & ~c_match)
        return occ, grave, kh, ws, slots, done, offset

    # initial carries derive from varying inputs so the loop is well-typed
    # under shard_map's varying-manual-axes tracking (and a no-op otherwise)
    zero_i32 = (khash * 0).astype(jnp.int32)
    occ, grave, kh, ws, slots, done, _ = jax.lax.fori_loop(
        0,
        MAX_PROBES,
        body,
        (
            store["occ"],
            store["grave"],
            store["khash"],
            store["wstart"],
            zero_i32 + dump,
            zero_i32 != 0,
            zero_i32,
        ),
    )
    store = dict(store)
    store["khash"], store["wstart"] = kh, ws
    store["overflow"] = store["overflow"] + jnp.sum(active & ~done)
    # key reprs/null bits: idempotent writes (same key ⇒ same repr); matched
    # graves come back alive
    target = jnp.where(done, slots, dump)
    occ = occ.at[target].set(True)
    occ = occ.at[capacity].set(False)
    store["occ"] = occ
    store["grave"] = grave.at[target].set(False)
    for i, repr_col in enumerate(key_reprs):
        store[f"key{i}"] = store[f"key{i}"].at[target].set(repr_col)
    store["knull"] = store["knull"].at[target].set(knull)
    return store, jnp.where(done, slots, dump)


def probe_find(
    store: Dict[str, jnp.ndarray],
    capacity: int,
    khash: jnp.ndarray,
    wstart: jnp.ndarray,
    active: jnp.ndarray,
) -> jnp.ndarray:
    """Find-only probe (no insertion): one slot per active row, or the dump
    slot ``capacity`` when the key is absent.  Used by join lookups against
    a keyed store."""
    mask = capacity - 1
    dump = jnp.int32(capacity)
    base = (mix64(khash ^ (wstart * _GOLD)) & mask).astype(jnp.int32)

    def body(_, carry):
        slots, done, offset = carry
        cand = ((base + offset) & mask).astype(jnp.int32)
        c_occ = store["occ"][cand]
        c_used = c_occ | store["grave"][cand]
        # live match only — a grave means the key was deleted
        c_match = c_occ & (store["khash"][cand] == khash) & (
            store["wstart"][cand] == wstart
        )
        newly = ~done & active & c_match
        slots = jnp.where(newly, cand, slots)
        # a truly-empty slot terminates the probe sequence: key absent
        # (graves are walked past — the key may live further down)
        done = done | newly | ~c_used
        offset = offset + (~done & active)
        return slots, done, offset

    zero_i32 = (khash * 0).astype(jnp.int32)
    slots, _, _ = jax.lax.fori_loop(
        0, MAX_PROBES, body, (zero_i32 + dump, zero_i32 != 0, zero_i32)
    )
    return jnp.where(active, slots, dump)


def _slot_ranks(eff: jnp.ndarray) -> jnp.ndarray:
    """Arrival-stable rank of each row within its slot group (rows at the
    dump slot still get ranks — callers mask them out)."""
    n = eff.shape[0]
    order = jnp.argsort(eff, stable=True)
    ss = eff[order]
    idx = jnp.arange(n, dtype=jnp.int32)
    prev = jnp.concatenate([jnp.full((1,), -1, ss.dtype), ss[:-1]])
    run_start = jax.lax.cummax(jnp.where(ss != prev, idx, -1).at[0].set(0))
    return jnp.zeros(n, jnp.int32).at[order].set(idx - run_start)


def _sort_desc(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sort(x, axis=-1)[..., ::-1]


def _desc_key(vals: jnp.ndarray) -> jnp.ndarray:
    """A monotone-decreasing sort key (no overflow at dtype min)."""
    if jnp.issubdtype(vals.dtype, jnp.integer):
        return ~vals
    return -vals


def _batch_membership(cnt_col, data_col, vbit_col, K, eff0, vals, vbits):
    """Shared set-style batched dedup: (member-of-stored-prefix,
    first-in-batch-occurrence) masks for per-slot (value, null-bit) pairs —
    used by collect_set insertion and histogram/attr appends."""
    n = vals.shape[0]
    cnt_before_row = cnt_col[eff0]
    pos_idx = jnp.arange(K)
    occ_mask = pos_idx[None, :] < jnp.minimum(cnt_before_row, K)[:, None]
    eq = (data_col[eff0] == vals[:, None]) & (vbit_col[eff0] == vbits[:, None])
    member = jnp.any(eq & occ_mask, axis=1)
    order = jnp.lexsort((vbits, vals, eff0))
    so_eff, so_v, so_b = eff0[order], vals[order], vbits[order]
    diff = (
        (so_eff != jnp.concatenate([jnp.full((1,), -1, so_eff.dtype), so_eff[:-1]]))
        | (so_v != jnp.concatenate([so_v[:1] + 1, so_v[:-1]]))
        | (so_b != jnp.concatenate([so_b[:1] + 1, so_b[:-1]]))
    ).at[0].set(True)
    firsts = jnp.zeros(n, bool).at[order].set(diff)
    return member, firsts


def _vec_collect(store, layout, j, contribs, slots, dump):
    """collect_list/collect_set/earliest-N/latest-N group fold: components
    j (count), j+1 (values, width K), j+2 (element null bits, width K)."""
    data_comp = layout.components[j + 1]
    K = data_comp.width
    cnt_col = store[f"a{j}"]
    data_col = store[f"a{j + 1}"]
    vbit_col = store[f"a{j + 2}"]
    ok = contribs[j] > 0
    vals = contribs[j + 1].astype(data_col.dtype)
    vbits = contribs[j + 2].astype(vbit_col.dtype)
    n = vals.shape[0]
    contributing = ok & (slots != dump)
    if data_comp.mode == "set":
        # membership against stored elements (value + null-bit equality over
        # the occupied prefix), then in-batch first-occurrence dedup
        eff0 = jnp.where(contributing, slots, dump)
        member, firsts = _batch_membership(
            cnt_col, data_col, vbit_col, K, eff0, vals, vbits
        )
        new = contributing & ~member & firsts
    else:
        new = contributing
    eff = jnp.where(new, slots, dump)
    rank = _slot_ranks(eff)
    pos = cnt_col[eff].astype(jnp.int32) + rank
    if data_comp.mode == "ring":
        # >K contributions to one slot in a batch wrap the ring: keep only
        # the LAST K so scatter positions stay distinct (duplicate indices
        # in .at[].set resolve in undefined order)
        n_slot = jnp.zeros(layout.capacity + 1, jnp.int32).at[eff].add(
            new.astype(jnp.int32)
        )
        end_pos = cnt_col[eff].astype(jnp.int32) + n_slot[eff]
        write = new & (pos >= end_pos - K)
        tgt_pos = (pos % K).astype(jnp.int32)
    else:  # 'append' / 'set': capped at K, count keeps the logical total
        write = new & (pos < K)
        tgt_pos = jnp.clip(pos, 0, K - 1)
    tgt_slot = jnp.where(write, eff, dump)
    store[f"a{j + 1}"] = data_col.at[tgt_slot, tgt_pos].set(vals)
    store[f"a{j + 2}"] = vbit_col.at[tgt_slot, tgt_pos].set(vbits)
    store[f"a{j}"] = cnt_col.at[eff].add(new.astype(cnt_col.dtype))


def _vec_remove(store, layout, j, contribs, slots, dump):
    """Collect-list undo: remove the FIRST stored occurrence of each undo
    row's value from its slot's vector, compacting left (order-preserving)
    — CollectListUdaf.undo semantics for table-aggregation retractions.

    Duplicate undo rows for one (slot, value) claim successive occurrences;
    one winner row per touched slot gathers the slot's removal bitmap,
    compacts the K-vector, and scatters it back."""
    data_comp = layout.components[j + 1]
    K = data_comp.width
    cnt_col = store[f"a{j}"]
    data_col = store[f"a{j + 1}"]
    vbit_col = store[f"a{j + 2}"]
    head = contribs[j]
    vals = contribs[j + 1].astype(data_col.dtype)
    vbits = contribs[j + 2].astype(vbit_col.dtype)
    n = vals.shape[0]
    pos_idx = jnp.arange(K, dtype=jnp.int32)
    rowidx = jnp.arange(n, dtype=jnp.int32)
    removing = (head < 0) & (slots != dump)
    eff = jnp.where(removing, slots, dump)
    # rank among same-(slot, value) undo rows: the r-th duplicate claims
    # the r-th stored occurrence
    order = jnp.lexsort((rowidx, vbits, vals, eff))
    so_eff, so_v, so_b = eff[order], vals[order], vbits[order]
    prev_eff = jnp.concatenate([jnp.full((1,), -1, so_eff.dtype), so_eff[:-1]])
    prev_v = jnp.concatenate([so_v[:1] + 1, so_v[:-1]])
    prev_b = jnp.concatenate([so_b[:1] + 1, so_b[:-1]])
    new_run = (so_eff != prev_eff) | (so_v != prev_v) | (so_b != prev_b)
    sidx = jnp.arange(n, dtype=jnp.int32)
    run_start = jax.lax.cummax(jnp.where(new_run, sidx, 0))
    row_rank = jnp.zeros(n, jnp.int32).at[order].set(sidx - run_start)
    occ = pos_idx[None, :] < jnp.minimum(cnt_col[eff], K).astype(jnp.int32)[:, None]
    match = (
        (data_col[eff] == vals[:, None])
        & (vbit_col[eff] == vbits[:, None])
        & occ
    )
    pos_rank = jnp.cumsum(match, axis=1) - 1
    claim = match & (pos_rank == row_rank[:, None]) & removing[:, None]
    # one winner row per touched slot accumulates the slot's bitmap
    first = jnp.full(layout.capacity + 1, n, jnp.int32).at[eff].min(
        jnp.where(removing, rowidx, n)
    )
    wrow = jnp.where(removing, first[eff], n)  # n = discard row
    rem = jnp.zeros((n + 1, K), bool).at[wrow].max(claim)[:n]
    is_winner = removing & (first[eff] == rowidx)
    effw = jnp.where(is_winner, slots, dump)
    cnt_w = jnp.minimum(cnt_col[effw], K).astype(jnp.int32)
    cur_d = data_col[effw]
    cur_b = vbit_col[effw]
    keep = (~rem) & (pos_idx[None, :] < cnt_w[:, None])
    new_pos = (jnp.cumsum(keep, axis=1) - 1).astype(jnp.int32)
    tgt_pos = jnp.where(keep, new_pos, K - 1)
    out_d = jnp.zeros((n, K), cur_d.dtype).at[rowidx[:, None], tgt_pos].add(
        jnp.where(keep, cur_d, 0)
    )
    out_b = jnp.zeros((n, K), cur_b.dtype).at[rowidx[:, None], tgt_pos].add(
        jnp.where(keep, cur_b, 0)
    )
    n_removed = jnp.sum(rem & (pos_idx[None, :] < cnt_w[:, None]), axis=1)
    store[f"a{j + 1}"] = data_col.at[effw].set(out_d)
    store[f"a{j + 2}"] = vbit_col.at[effw].set(out_b)
    store[f"a{j}"] = cnt_col.at[effw].add(-n_removed.astype(cnt_col.dtype))


def _vec_hist(store, layout, j, contribs, slots, dump):
    """Histogram group fold: components j (distinct count head), j+1
    (value codes, width K), j+2 (element bits), j+3 (per-element counts).

    Phase 1 appends NEW distinct values set-style (insert rows only —
    head contribution > 0); phase 2 scatter-adds each row's signed head
    contribution to its value's count, so undo decrements in place and
    zero-count entries read as absent at finalize."""
    data_comp = layout.components[j + 1]
    K = data_comp.width
    cnt_col = store[f"a{j}"]
    data_col = store[f"a{j + 1}"]
    vbit_col = store[f"a{j + 2}"]
    num_col = store[f"a{j + 3}"]
    head = contribs[j]
    vals = contribs[j + 1].astype(data_col.dtype)
    vbits = contribs[j + 2].astype(vbit_col.dtype)
    n = vals.shape[0]
    contributing = (head != 0) & (slots != dump)
    inserting = (head > 0) & (slots != dump)
    pos_idx = jnp.arange(K)
    # ---- phase 1: set-style append of new distinct values (cap K)
    eff0 = jnp.where(inserting, slots, dump)
    member, firsts = _batch_membership(
        cnt_col, data_col, vbit_col, K, eff0, vals, vbits
    )
    new = inserting & ~member & firsts
    eff = jnp.where(new, slots, dump)
    rank = _slot_ranks(eff)
    pos = cnt_col[eff].astype(jnp.int32) + rank
    write = new & (pos < K)
    tgt_pos = jnp.clip(pos, 0, K - 1)
    tgt_slot = jnp.where(write, eff, dump)
    data_col = data_col.at[tgt_slot, tgt_pos].set(vals)
    vbit_col = vbit_col.at[tgt_slot, tgt_pos].set(vbits)
    cnt_col = cnt_col.at[eff].add(jnp.where(write, 1, 0).astype(cnt_col.dtype))
    # ---- phase 2: signed count increment at each row's member position
    eff2 = jnp.where(contributing, slots, dump)
    occ2 = pos_idx[None, :] < jnp.minimum(cnt_col[eff2], K)[:, None]
    eq2 = (
        (data_col[eff2] == vals[:, None])
        & (vbit_col[eff2] == vbits[:, None])
        & occ2
    )
    found = jnp.any(eq2, axis=1)
    pos2 = jnp.argmax(eq2, axis=1).astype(jnp.int32)
    t_slot = jnp.where(contributing & found, eff2, dump)
    num_col = num_col.at[t_slot, pos2].add(head.astype(num_col.dtype))
    store[f"a{j}"] = cnt_col
    store[f"a{j + 1}"] = data_col
    store[f"a{j + 2}"] = vbit_col
    store[f"a{j + 3}"] = num_col


def _vec_topk(store, comp, j, contrib, slots, dump):
    """Top-K fold: per-slot batch candidates (sorted) merged with the stored
    K values; sentinel (= comp.init, the dtype floor) marks empty entries."""
    K = comp.width
    col = store[f"a{j}"]
    dt = col.dtype
    sent = jnp.asarray(comp.init, dt)
    vals = contrib.astype(dt)
    n = vals.shape[0]
    eff = jnp.where((vals != sent) & (slots != dump), slots, dump)
    order = jnp.lexsort((jnp.arange(n), _desc_key(vals), eff))
    so_eff, so_v = eff[order], vals[order]
    if comp.mode == "distinct":
        # in-batch dedup BEFORE windowing: duplicates would otherwise
        # consume candidate-window slots and hide distinct values ranked
        # past position K
        dup = (
            (so_eff == jnp.concatenate([jnp.full((1,), -1, so_eff.dtype), so_eff[:-1]]))
            & (so_v == jnp.concatenate([so_v[:1], so_v[:-1]]))
        ).at[0].set(False)
        so_eff = jnp.where(dup, dump, so_eff)
        so_v = jnp.where(dup, sent, so_v)
        order2 = jnp.lexsort((jnp.arange(n), _desc_key(so_v), so_eff))
        so_eff, so_v = so_eff[order2], so_v[order2]
    idx = jnp.arange(n, dtype=jnp.int32)
    prev = jnp.concatenate([jnp.full((1,), -1, so_eff.dtype), so_eff[:-1]])
    run_start = jax.lax.cummax(jnp.where(so_eff != prev, idx, -1).at[0].set(0))
    winner = (idx == run_start) & (so_eff != dump)
    offs = idx[:, None] + jnp.arange(K, dtype=jnp.int32)[None, :]
    gidx = jnp.minimum(offs, n - 1)
    cand = jnp.where(
        (so_eff[gidx] == so_eff[:, None]) & (offs < n), so_v[gidx], sent
    )
    allv = jnp.concatenate([cand, col[so_eff]], axis=1)
    if comp.mode == "distinct":
        s = _sort_desc(allv)
        dup = jnp.concatenate(
            [jnp.zeros((n, 1), bool), s[:, 1:] == s[:, :-1]], axis=1
        )
        allv = jnp.where(dup, sent, s)
    top = _sort_desc(allv)[:, :K]
    tgt = jnp.where(winner, so_eff, dump)
    store[f"a{j}"] = col.at[tgt].set(top)


def scatter_combine(
    store: Dict[str, jnp.ndarray],
    layout: StoreLayout,
    slots: jnp.ndarray,
    contribs: Sequence[jnp.ndarray],
    vec_undo: bool = False,
) -> Dict[str, jnp.ndarray]:
    """Fold per-row contributions into the store (KudafAggregator.apply
    analog, batched: duplicate slots accumulate in one scatter).

    'argset' components carry the payload of an arg-min/max: after the
    nearest preceding orderable component is combined, the row whose
    contribution equals the slot's NEW order value (unique sequence numbers
    guarantee a single winner) writes the payload.  'vec_count'/'topk' head
    vector-state groups (collect/topk families, see AggComponent)."""
    store = dict(store)
    dump = jnp.int32(layout.capacity)
    last_order: int = 0
    j = 0
    ncomp = len(layout.components)
    while j < ncomp:
        comp = layout.components[j]
        contrib = contribs[j]
        col = store[f"a{j}"]
        if comp.combine == "vec_count":
            if comp.mode == "hist":
                _vec_hist(store, layout, j, contribs, slots, dump)
                j += 4
                continue
            if vec_undo:
                # table-aggregation undo side: negative head contributions
                # remove stored occurrences (no-op on the apply side)
                _vec_remove(store, layout, j, contribs, slots, dump)
            _vec_collect(store, layout, j, contribs, slots, dump)
            j += 3
            continue
        if comp.combine == "topk":
            _vec_topk(store, comp, j, contrib, slots, dump)
            j += 1
            continue
        ref = col.at[slots]
        if comp.combine == "add":
            store[f"a{j}"] = ref.add(contrib.astype(col.dtype))
            last_order = j
        elif comp.combine == "min":
            store[f"a{j}"] = ref.min(contrib.astype(col.dtype))
            last_order = j
        elif comp.combine == "max":
            store[f"a{j}"] = ref.max(contrib.astype(col.dtype))
            last_order = j
        elif comp.combine == "argset":
            order_new = store[f"a{last_order}"]
            winner = (slots != dump) & (
                contribs[last_order] == order_new[slots]
            )
            tgt = jnp.where(winner, slots, dump)
            store[f"a{j}"] = col.at[tgt].set(contrib.astype(col.dtype))
        else:  # pragma: no cover
            raise ValueError(comp.combine)
        j += 1
    store["dirty"] = store["dirty"].at[slots].set(True)
    store["dirty"] = store["dirty"].at[layout.capacity].set(False)
    return store


def winners_per_slot(slots: jnp.ndarray, active: jnp.ndarray, capacity: int) -> jnp.ndarray:
    """Mask selecting one representative row per distinct touched slot
    (used to emit exactly one change per key per batch)."""
    n = slots.shape[0]
    rowidx = jnp.arange(n, dtype=jnp.int32)
    dump = jnp.int32(capacity)
    first = jnp.full(capacity + 1, n, jnp.int32)
    first = first.at[jnp.where(active, slots, dump)].min(rowidx)
    return active & (slots != dump) & (first[slots] == rowidx)


def np_mix64(h: np.ndarray) -> np.ndarray:
    """Host (numpy) replica of mix64 — must stay bit-identical; used when
    rebuilding a store into a larger capacity."""
    u = np.asarray(h).astype(np.int64).view(np.uint64).copy()
    u ^= u >> np.uint64(30)
    u *= np.uint64(0xBF58476D1CE4E5B9)
    u ^= u >> np.uint64(27)
    u *= np.uint64(0x94D049BB133111EB)
    u ^= u >> np.uint64(31)
    return u.view(np.int64)


def host_insert(
    occ: np.ndarray,
    kh: np.ndarray,
    ws: np.ndarray,
    capacity: int,
    khash: np.ndarray,
    wstart: np.ndarray,
) -> np.ndarray:
    """Vectorized numpy insert of unique (khash, wstart) keys into a store
    (occ/kh/ws mutated in place); returns per-key slots.  The host half of
    store growth — the RocksDB-compaction analog."""
    n = len(khash)
    mask = capacity - 1
    wmul = (
        np.asarray(wstart).astype(np.int64).view(np.uint64)
        * np.uint64(0x9E3779B97F4A7C15)
    ).view(np.int64)
    base = (np_mix64(np.asarray(khash) ^ wmul) & mask).astype(np.int64)
    slots = np.full(n, -1, np.int64)
    offset = np.zeros(n, np.int64)
    done = np.zeros(n, bool)
    for _ in range(4 * MAX_PROBES):
        if done.all():
            break
        cand = (base + offset) & mask
        c_occ = occ[cand]
        match = c_occ & (kh[cand] == khash) & (ws[cand] == wstart)
        newly = ~done & match
        slots[newly] = cand[newly]
        done |= newly
        want = ~done & ~c_occ
        claim = np.full(capacity, n, np.int64)
        np.minimum.at(claim, cand[want], np.nonzero(want)[0])
        winner = want & (claim[cand] == np.arange(n))
        occ[cand[winner]] = True
        kh[cand[winner]] = khash[winner]
        ws[cand[winner]] = wstart[winner]
        slots[winner] = cand[winner]
        done |= winner
        offset += (~done & c_occ & ~match).astype(np.int64)
    if not done.all():
        raise RuntimeError("host_insert: probe limit exceeded (table too full)")
    return slots


