"""HBM-resident keyed state store — the RocksDB analog.

The reference materializes every aggregation/table in RocksDB via JNI
(ksqldb-rocksdb-config-setter/.../KsqlBoundedMemoryRocksDBConfigSetter.java:35,
Materialized stores in StreamAggregateBuilder.java).  The TPU design keeps
state *on device*: an open-addressing hash table laid out as structure-of-
arrays in HBM, updated by vectorized gather/scatter — no sort, no host
round-trip, no dynamic shapes.

Layout (all arrays length ``capacity + 1``; the last slot is the *dump slot*
that absorbs writes from inactive/overflowed lanes so every scatter has a
static target):

* ``occ``      bool      — slot occupied
* ``khash``    int64     — combined group-key hash (probe identity)
* ``wstart``   int64     — window start ms (0 when unwindowed)
* ``key<i>``   int64     — raw 64-bit repr of key column i (for emission)
* ``knull``    int32     — bitmask of NULL key columns
* ``dirty``    bool      — updated since last suppress flush (EMIT FINAL)
* ``a<j>``     per-aggregate component arrays (see device_aggs.py)

Insert algorithm (per batch, fully vectorized over rows):
repeat ``MAX_PROBES`` times — gather candidate slot; if it matches, resolve;
if empty, *claim* it by scatter-min of the row index and let the winner
write its key (losers re-examine the slot next round: if the winner had the
same key they resolve to it, otherwise they advance along the probe
sequence).  Rows still unresolved after the loop land in the dump slot and
are counted in ``overflow`` — the host reacts by growing the table
(host-side rebuild), the moral equivalent of RocksDB compaction.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

MAX_PROBES = 32

_M1 = np.array(0xBF58476D1CE4E5B9, dtype=np.uint64).view(np.int64)
_M2 = np.array(0x94D049BB133111EB, dtype=np.uint64).view(np.int64)
_GOLD = np.array(0x9E3779B97F4A7C15, dtype=np.uint64).view(np.int64)


def mix64(h: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 finalizer (logical shifts; int64 throughout)."""
    h = h ^ jax.lax.shift_right_logical(h, 30)
    h = h * _M1
    h = h ^ jax.lax.shift_right_logical(h, 27)
    h = h * _M2
    h = h ^ jax.lax.shift_right_logical(h, 31)
    return h


def combine_hash(parts: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Fold per-key-column 64-bit reprs into one group hash."""
    h = jnp.full_like(parts[0], _GOLD)
    for p in parts:
        h = mix64(h ^ p + _GOLD)
    return h


@dataclasses.dataclass(frozen=True)
class AggComponent:
    """One scatter-combined state column of an aggregate."""

    combine: str  # 'add' | 'min' | 'max'
    dtype: str  # numpy dtype name
    init: float  # fill value for empty slots


@dataclasses.dataclass(frozen=True)
class StoreLayout:
    capacity: int  # power of two
    num_keys: int
    components: Tuple[AggComponent, ...]
    windowed: bool = False

    def __post_init__(self):
        if self.capacity & (self.capacity - 1):
            raise ValueError("store capacity must be a power of two")


def init_store(layout: StoreLayout) -> Dict[str, jnp.ndarray]:
    c1 = layout.capacity + 1
    store = {
        "occ": jnp.zeros(c1, bool),
        # tombstoned slots: freed (evicted/deleted) but still part of probe
        # chains — linear probing must walk past them or keys inserted
        # beyond would split into duplicate slots; compaction (host rebuild
        # in _grow) reclaims them
        "grave": jnp.zeros(c1, bool),
        "khash": jnp.zeros(c1, jnp.int64),
        "wstart": jnp.zeros(c1, jnp.int64),
        "knull": jnp.zeros(c1, jnp.int32),
        "dirty": jnp.zeros(c1, bool),
        "max_ts": jnp.array(np.iinfo(np.int64).min, jnp.int64),
        "overflow": jnp.zeros((), jnp.int64),
    }
    for i in range(layout.num_keys):
        store[f"key{i}"] = jnp.zeros(c1, jnp.int64)
    for j, comp in enumerate(layout.components):
        store[f"a{j}"] = jnp.full(c1, comp.init, dtype=np.dtype(comp.dtype))
    return store


def probe_insert(
    store: Dict[str, jnp.ndarray],
    capacity: int,
    khash: jnp.ndarray,
    wstart: jnp.ndarray,
    key_reprs: Sequence[jnp.ndarray],
    knull: jnp.ndarray,
    active: jnp.ndarray,
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """Resolve (and create) one slot per active row; returns (store, slots).

    ``slots`` is int32 per row; inactive/overflowed rows get the dump slot
    ``capacity``.
    """
    n = khash.shape[0]
    mask = capacity - 1
    dump = jnp.int32(capacity)
    rowidx = jnp.arange(n, dtype=jnp.int32)
    big = jnp.int32(n)
    base = (mix64(khash ^ (wstart * _GOLD)) & mask).astype(jnp.int32)

    def body(_, carry):
        occ, grave, kh, ws, slots, done, offset = carry
        cand = ((base + offset) & mask).astype(jnp.int32)
        c_occ = occ[cand]
        c_grave = grave[cand]
        c_used = c_occ | c_grave
        # a matching grave is reclaimed (same key re-inserted after free)
        c_match = c_used & (kh[cand] == khash) & (ws[cand] == wstart)
        newly = ~done & active & c_match
        slots = jnp.where(newly, cand, slots)
        done = done | newly
        # claim truly-empty candidates: lowest row index wins the slot.
        # Graves are NOT claimable — the key may live further down the
        # chain; compaction reclaims them.
        want = ~done & active & ~c_used
        claim = jnp.full(capacity + 1, big, jnp.int32)
        claim = claim.at[jnp.where(want, cand, dump)].min(rowidx)
        winner = want & (claim[cand] == rowidx)
        target = jnp.where(winner, cand, dump)
        occ = occ.at[target].set(True)
        occ = occ.at[capacity].set(False)
        kh = kh.at[target].set(khash)
        ws = ws.at[target].set(wstart)
        slots = jnp.where(winner, cand, slots)
        done = done | winner
        # used-by-other: advance along probe sequence; claim losers
        # re-examine the same slot next round (winner may share their key)
        offset = offset + (~done & active & c_used & ~c_match)
        return occ, grave, kh, ws, slots, done, offset

    # initial carries derive from varying inputs so the loop is well-typed
    # under shard_map's varying-manual-axes tracking (and a no-op otherwise)
    zero_i32 = (khash * 0).astype(jnp.int32)
    occ, grave, kh, ws, slots, done, _ = jax.lax.fori_loop(
        0,
        MAX_PROBES,
        body,
        (
            store["occ"],
            store["grave"],
            store["khash"],
            store["wstart"],
            zero_i32 + dump,
            zero_i32 != 0,
            zero_i32,
        ),
    )
    store = dict(store)
    store["khash"], store["wstart"] = kh, ws
    store["overflow"] = store["overflow"] + jnp.sum(active & ~done)
    # key reprs/null bits: idempotent writes (same key ⇒ same repr); matched
    # graves come back alive
    target = jnp.where(done, slots, dump)
    occ = occ.at[target].set(True)
    occ = occ.at[capacity].set(False)
    store["occ"] = occ
    store["grave"] = grave.at[target].set(False)
    for i, repr_col in enumerate(key_reprs):
        store[f"key{i}"] = store[f"key{i}"].at[target].set(repr_col)
    store["knull"] = store["knull"].at[target].set(knull)
    return store, jnp.where(done, slots, dump)


def probe_find(
    store: Dict[str, jnp.ndarray],
    capacity: int,
    khash: jnp.ndarray,
    wstart: jnp.ndarray,
    active: jnp.ndarray,
) -> jnp.ndarray:
    """Find-only probe (no insertion): one slot per active row, or the dump
    slot ``capacity`` when the key is absent.  Used by join lookups against
    a keyed store."""
    mask = capacity - 1
    dump = jnp.int32(capacity)
    base = (mix64(khash ^ (wstart * _GOLD)) & mask).astype(jnp.int32)

    def body(_, carry):
        slots, done, offset = carry
        cand = ((base + offset) & mask).astype(jnp.int32)
        c_occ = store["occ"][cand]
        c_used = c_occ | store["grave"][cand]
        # live match only — a grave means the key was deleted
        c_match = c_occ & (store["khash"][cand] == khash) & (
            store["wstart"][cand] == wstart
        )
        newly = ~done & active & c_match
        slots = jnp.where(newly, cand, slots)
        # a truly-empty slot terminates the probe sequence: key absent
        # (graves are walked past — the key may live further down)
        done = done | newly | ~c_used
        offset = offset + (~done & active)
        return slots, done, offset

    zero_i32 = (khash * 0).astype(jnp.int32)
    slots, _, _ = jax.lax.fori_loop(
        0, MAX_PROBES, body, (zero_i32 + dump, zero_i32 != 0, zero_i32)
    )
    return jnp.where(active, slots, dump)


def scatter_combine(
    store: Dict[str, jnp.ndarray],
    layout: StoreLayout,
    slots: jnp.ndarray,
    contribs: Sequence[jnp.ndarray],
) -> Dict[str, jnp.ndarray]:
    """Fold per-row contributions into the store (KudafAggregator.apply
    analog, batched: duplicate slots accumulate in one scatter).

    'argset' components carry the payload of an arg-min/max: after the
    nearest preceding orderable component is combined, the row whose
    contribution equals the slot's NEW order value (unique sequence numbers
    guarantee a single winner) writes the payload."""
    store = dict(store)
    dump = jnp.int32(layout.capacity)
    last_order: int = 0
    for j, (comp, contrib) in enumerate(zip(layout.components, contribs)):
        col = store[f"a{j}"]
        ref = col.at[slots]
        if comp.combine == "add":
            store[f"a{j}"] = ref.add(contrib.astype(col.dtype))
            last_order = j
        elif comp.combine == "min":
            store[f"a{j}"] = ref.min(contrib.astype(col.dtype))
            last_order = j
        elif comp.combine == "max":
            store[f"a{j}"] = ref.max(contrib.astype(col.dtype))
            last_order = j
        elif comp.combine == "argset":
            order_new = store[f"a{last_order}"]
            winner = (slots != dump) & (
                contribs[last_order] == order_new[slots]
            )
            tgt = jnp.where(winner, slots, dump)
            store[f"a{j}"] = col.at[tgt].set(contrib.astype(col.dtype))
        else:  # pragma: no cover
            raise ValueError(comp.combine)
    store["dirty"] = store["dirty"].at[slots].set(True)
    store["dirty"] = store["dirty"].at[layout.capacity].set(False)
    return store


def winners_per_slot(slots: jnp.ndarray, active: jnp.ndarray, capacity: int) -> jnp.ndarray:
    """Mask selecting one representative row per distinct touched slot
    (used to emit exactly one change per key per batch)."""
    n = slots.shape[0]
    rowidx = jnp.arange(n, dtype=jnp.int32)
    dump = jnp.int32(capacity)
    first = jnp.full(capacity + 1, n, jnp.int32)
    first = first.at[jnp.where(active, slots, dump)].min(rowidx)
    return active & (slots != dump) & (first[slots] == rowidx)


def np_mix64(h: np.ndarray) -> np.ndarray:
    """Host (numpy) replica of mix64 — must stay bit-identical; used when
    rebuilding a store into a larger capacity."""
    u = np.asarray(h).astype(np.int64).view(np.uint64).copy()
    u ^= u >> np.uint64(30)
    u *= np.uint64(0xBF58476D1CE4E5B9)
    u ^= u >> np.uint64(27)
    u *= np.uint64(0x94D049BB133111EB)
    u ^= u >> np.uint64(31)
    return u.view(np.int64)


def host_insert(
    occ: np.ndarray,
    kh: np.ndarray,
    ws: np.ndarray,
    capacity: int,
    khash: np.ndarray,
    wstart: np.ndarray,
) -> np.ndarray:
    """Vectorized numpy insert of unique (khash, wstart) keys into a store
    (occ/kh/ws mutated in place); returns per-key slots.  The host half of
    store growth — the RocksDB-compaction analog."""
    n = len(khash)
    mask = capacity - 1
    wmul = (
        np.asarray(wstart).astype(np.int64).view(np.uint64)
        * np.uint64(0x9E3779B97F4A7C15)
    ).view(np.int64)
    base = (np_mix64(np.asarray(khash) ^ wmul) & mask).astype(np.int64)
    slots = np.full(n, -1, np.int64)
    offset = np.zeros(n, np.int64)
    done = np.zeros(n, bool)
    for _ in range(4 * MAX_PROBES):
        if done.all():
            break
        cand = (base + offset) & mask
        c_occ = occ[cand]
        match = c_occ & (kh[cand] == khash) & (ws[cand] == wstart)
        newly = ~done & match
        slots[newly] = cand[newly]
        done |= newly
        want = ~done & ~c_occ
        claim = np.full(capacity, n, np.int64)
        np.minimum.at(claim, cand[want], np.nonzero(want)[0])
        winner = want & (claim[cand] == np.arange(n))
        occ[cand[winner]] = True
        kh[cand[winner]] = khash[winner]
        ws[cand[winner]] = wstart[winner]
        slots[winner] = cand[winner]
        done |= winner
        offset += (~done & c_occ & ~match).astype(np.int64)
    if not done.all():
        raise RuntimeError("host_insert: probe limit exceeded (table too full)")
    return slots


