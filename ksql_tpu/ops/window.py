"""Event-time window assignment — static-shape, branch-free.

The reference assigns windows per record inside Kafka Streams
(TimeWindows/SessionWindows via StreamAggregateBuilder.java:142-352).  On
device, assignment is columnar arithmetic over the timestamp vector:

* TUMBLING: one window per row — ``start = ts - ts mod size``.
* HOPPING: every row belongs to ``k = ceil(size/advance)`` windows (k is a
  compile-time constant), so the batch is expanded k-fold by tiling — XLA
  sees a static (k·n)-row batch; out-of-range expansions are masked, never
  branched.

SESSION windows are data-dependent merges and stay on the row oracle (their
segment-scan device formulation is future work, noted in SURVEY §7).

Stream slicing (the Partial Partial Aggregates / Enthuse formulation): the
k-fold hopping expansion is the *baseline*; decomposable aggregates instead
assign each record to exactly ONE slice of width ``gcd(size, advance)`` and
combine the covering slices per window at emission.  Slice boundaries
subdivide both the advance grid and the window-size grid, so every record
in a slice belongs to exactly the same set of covering windows — the
defining property that makes per-slice partials shareable across the
windows (and, one level up, across a whole *window family* of queries).
The helpers here are the pure slice-grid arithmetic; the ring-store layout
and combine kernels live in runtime/lowering.py.
"""

from __future__ import annotations

import math
from typing import Dict, Tuple

import jax.numpy as jnp


def tumbling_starts(ts: jnp.ndarray, size_ms: int) -> jnp.ndarray:
    return ts - jnp.remainder(ts, size_ms)


def hopping_expansion(size_ms: int, advance_ms: int) -> int:
    return -(-size_ms // advance_ms)  # ceil


# ------------------------------------------------------------- stream slicing
def slice_width(size_ms: int, advance_ms: int) -> int:
    """Width of one slice for a (size, advance) hopping window — the finest
    grid on which both window starts (advance-aligned) and window ends
    (start + size) land, so a slice is never split by a window boundary."""
    return math.gcd(size_ms, advance_ms)


def slices_per_window(size_ms: int, width_ms: int) -> int:
    """Covering slices per window (width divides size by construction)."""
    return size_ms // width_ms


def slice_starts(ts: jnp.ndarray, width_ms: int) -> jnp.ndarray:
    """The one slice each record belongs to (cf. the k-fold
    hopping_starts expansion this replaces)."""
    return ts - jnp.remainder(ts, width_ms)


def hopping_starts(
    ts: jnp.ndarray, size_ms: int, advance_ms: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expand n rows to (k·n) window assignments.

    Returns (starts[k*n], in_window[k*n]); caller tiles the row columns with
    ``jnp.tile(col, k)`` to match.  Ordering: expansion-major (all rows for
    hop 0, then hop 1, ...), matching ``jnp.tile``.
    """
    k = hopping_expansion(size_ms, advance_ms)
    n = ts.shape[0]
    first = ts - jnp.remainder(ts, advance_ms)  # newest window start
    hops = jnp.repeat(jnp.arange(k, dtype=ts.dtype), n)  # [0..0,1..1,...]
    ts_t = jnp.tile(ts, k)
    starts = jnp.tile(first, k) - hops * advance_ms
    ok = (starts >= 0) & (starts + size_ms > ts_t)
    return starts, ok


def expand(col: jnp.ndarray, k: int) -> jnp.ndarray:
    """Tile a row column to match hopping_starts' (k·n) expansion."""
    return jnp.tile(col, k)
