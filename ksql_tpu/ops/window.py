"""Event-time window assignment — static-shape, branch-free.

The reference assigns windows per record inside Kafka Streams
(TimeWindows/SessionWindows via StreamAggregateBuilder.java:142-352).  On
device, assignment is columnar arithmetic over the timestamp vector:

* TUMBLING: one window per row — ``start = ts - ts mod size``.
* HOPPING: every row belongs to ``k = ceil(size/advance)`` windows (k is a
  compile-time constant), so the batch is expanded k-fold by tiling — XLA
  sees a static (k·n)-row batch; out-of-range expansions are masked, never
  branched.

SESSION windows are data-dependent merges and stay on the row oracle (their
segment-scan device formulation is future work, noted in SURVEY §7).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp


def tumbling_starts(ts: jnp.ndarray, size_ms: int) -> jnp.ndarray:
    return ts - jnp.remainder(ts, size_ms)


def hopping_expansion(size_ms: int, advance_ms: int) -> int:
    return -(-size_ms // advance_ms)  # ceil


def hopping_starts(
    ts: jnp.ndarray, size_ms: int, advance_ms: int
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Expand n rows to (k·n) window assignments.

    Returns (starts[k*n], in_window[k*n]); caller tiles the row columns with
    ``jnp.tile(col, k)`` to match.  Ordering: expansion-major (all rows for
    hop 0, then hop 1, ...), matching ``jnp.tile``.
    """
    k = hopping_expansion(size_ms, advance_ms)
    n = ts.shape[0]
    first = ts - jnp.remainder(ts, advance_ms)  # newest window start
    hops = jnp.repeat(jnp.arange(k, dtype=ts.dtype), n)  # [0..0,1..1,...]
    ts_t = jnp.tile(ts, k)
    starts = jnp.tile(first, k) - hops * advance_ms
    ok = (starts >= 0) & (starts + size_ms > ts_t)
    return starts, ok


def expand(col: jnp.ndarray, k: int) -> jnp.ndarray:
    """Tile a row column to match hopping_starts' (k·n) expansion."""
    return jnp.tile(col, k)
