"""Device aggregation kernels: UDAF families as scatter-combined components.

The XLA analog of KudafAggregator.apply (ksqldb-execution/.../udaf/
KudafAggregator.java:56): each supported ``device_kind`` (declared on the
host Udaf in functions/udafs.py) decomposes into 'add'/'min'/'max' state
components that hash_store.scatter_combine folds in O(batch) scatters, plus
a ``finalize`` that maps slot state → output column (the result() analog).

Families whose state is inherently variable-size per key (collect_list,
topk, histogram, count_distinct exact) have no device decomposition and keep
the query on the row oracle.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ksql_tpu.common import types as T
from ksql_tpu.common.types import SqlBaseType, SqlType
from ksql_tpu.compiler.jax_expr import DCol, DeviceUnsupported
from ksql_tpu.ops.hash_store import AggComponent

_F64_MAX = np.finfo(np.float64).max
_I64_MAX = np.iinfo(np.int64).max
_I32_MAX = np.iinfo(np.int32).max


@dataclasses.dataclass
class DeviceAgg:
    """A compiled device aggregate: components + per-row contributions +
    finalizer."""

    components: Tuple[AggComponent, ...]
    # (args, row_active) -> per-component contribution arrays
    contribs: Callable[[Sequence[DCol], jnp.ndarray], List[jnp.ndarray]]
    # component slot arrays -> (data, valid)
    finalize: Callable[[Sequence[jnp.ndarray]], Tuple[jnp.ndarray, jnp.ndarray]]
    result_type: SqlType
    # when set, table-aggregation undo uses these contributions instead of
    # component-wise negation (families whose fold inverts differently,
    # e.g. histogram's signed count increments)
    undo_contribs: Optional[Callable] = None
    # when set, |component 0| exceeding this bound at emission means the
    # finalized value no longer round-trips its float64 carrier exactly
    # (DECIMAL SUM's int64 scaled accumulator past 2^53): the runtime
    # raises instead of emitting a silently drifted value
    exact_abs_bound: Optional[int] = None


def _numeric_data(a: DCol) -> jnp.ndarray:
    return a.data


def _minmax_dtype(t: SqlType):
    if t.base in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
        return np.float64, np.inf  # ±inf sentinels: data may contain ±F64_MAX
    if t.base == SqlBaseType.INTEGER:
        return np.int32, _I32_MAX
    return np.int64, _I64_MAX


#: hard ceiling on per-key vector state width (collect/topk); wider caps
#: keep the query on the oracle rather than blow up HBM
MAX_VEC_WIDTH = 4096

#: DECIMAL SUM exactness envelope: the certified number of max-magnitude
#: addends a per-key sum absorbs before its int64 accumulator could pass
#: 2^53 scaled units (where the float64 finalize stops being exact).  With
#: 10^p bounding one addend, device eligibility requires
#: 10^p * HEADROOM <= 2^53 — i.e. result precision <= 12
SUM_ACCUM_HEADROOM_ROWS = 1000


def _vec_dtype(t: SqlType):
    """Element storage dtype for vector state (strings/bytes carry their
    dictionary hash codes, booleans int8)."""
    if t.base in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
        return np.float64
    if t.base == SqlBaseType.BOOLEAN:
        return np.int8
    if t.base == SqlBaseType.INTEGER:
        return np.int32
    return np.int64


def compile_device_agg(
    kind: str,
    arg_types: Sequence[SqlType],
    result_type: SqlType,
    fname: str = "",
    literals: Sequence[object] = (),
) -> DeviceAgg:
    """Build the device decomposition for one aggregation call.  ``fname``
    disambiguates families sharing a kind (STDDEV_POP vs STDDEV_SAMP);
    ``literals`` are the values of trailing literal params (TOPK's k,
    earliest/latest's n and ignoreNulls) when statically known."""
    if kind == "count_star":
        return DeviceAgg(
            components=(AggComponent("add", "int64", 0),),
            contribs=lambda args, act, seq=None: [act.astype(jnp.int64)],
            finalize=lambda comps: (comps[0], jnp.ones_like(comps[0], bool)),
            result_type=T.BIGINT,
        )
    if kind == "count":
        return DeviceAgg(
            components=(AggComponent("add", "int64", 0),),
            contribs=lambda args, act, seq=None: [(act & args[0].valid).astype(jnp.int64)],
            finalize=lambda comps: (comps[0], jnp.ones_like(comps[0], bool)),
            result_type=T.BIGINT,
        )
    if kind == "sum":
        t = result_type
        if t.base == SqlBaseType.DECIMAL:
            # exact decimal folding: accumulate the SCALED UNSCALED value in
            # int64 (each ≤15-digit addend recovers exactly from its f64
            # carrier via round), so in-precision sums never drift the way
            # a raw f64 fold would; finalize rescales (≤15 digits: f64-exact)
            if 10 ** int(t.precision or 0) * SUM_ACCUM_HEADROOM_ROWS > 2 ** 53:
                # the ACCUMULATED sum, not just each addend, must survive
                # finalize's int64→float64 conversion (exact only below
                # 2^53 scaled units).  10^precision bounds one addend;
                # reserve headroom for SUM_ACCUM_HEADROOM_ROWS max-magnitude
                # rows — beyond that the device cannot certify exactness
                # statically, and the oracle's unbounded decimal arithmetic
                # keeps the query instead of silently drifting
                raise DeviceUnsupported(
                    f"DECIMAL({t.precision},{t.scale}) SUM can exceed the "
                    "2^53-exact device envelope (int64 accumulator decodes "
                    "through float64)"
                )
            scale_f = float(10 ** (t.scale or 0))
            return DeviceAgg(
                components=(AggComponent("add", "int64", 0),),
                contribs=lambda args, act, seq=None: [
                    jnp.where(
                        act & args[0].valid,
                        jnp.round(args[0].data * scale_f), 0.0,
                    ).astype(jnp.int64)
                ],
                finalize=lambda comps: (
                    comps[0].astype(jnp.float64) / scale_f,
                    jnp.ones(comps[0].shape, bool),
                ),
                result_type=t,
                # runtime backstop for the static gate above: a key whose
                # ACCUMULATED sum still crosses 2^53 scaled units (more
                # than the certified headroom of max-magnitude rows) is
                # detected at emission rather than silently drifting
                exact_abs_bound=2 ** 53,
            )
        dt = (
            np.float64
            if t.base == SqlBaseType.DOUBLE
            else (np.int32 if t.base == SqlBaseType.INTEGER else np.int64)
        )
        return DeviceAgg(
            components=(AggComponent("add", np.dtype(dt).name, 0),),
            contribs=lambda args, act, seq=None: [
                jnp.where(act & args[0].valid, args[0].data, 0).astype(dt)
            ],
            # SumKudaf: 0-initialized, nulls skipped ⇒ always non-null
            finalize=lambda comps: (comps[0], jnp.ones(comps[0].shape, bool)),
            result_type=t,
        )
    if kind in ("min", "max"):
        t = arg_types[0]
        if t.base in (SqlBaseType.STRING, SqlBaseType.BYTES):
            raise DeviceUnsupported("MIN/MAX over strings on device")
        dt, sentinel = _minmax_dtype(t)
        sign = 1 if kind == "min" else -1
        fill = sentinel if kind == "min" else (-sentinel if dt == np.float64 else -sentinel - 1)
        combine = kind

        def contribs(args, act, seq=None, fill=fill, dt=dt):
            ok = act & args[0].valid
            return [
                jnp.where(ok, args[0].data.astype(dt), jnp.asarray(fill, dt)),
                ok.astype(jnp.int32),
            ]

        def finalize(comps):
            seen = comps[1] > 0
            return comps[0], seen

        return DeviceAgg(
            components=(
                AggComponent(combine, np.dtype(dt).name, fill),
                AggComponent("max", "int32", 0),
            ),
            contribs=contribs,
            finalize=finalize,
            result_type=t,
        )
    if kind == "avg":
        def contribs(args, act, seq=None):
            ok = act & args[0].valid
            return [
                jnp.where(ok, args[0].data.astype(jnp.float64), 0.0),
                ok.astype(jnp.int64),
            ]

        def finalize(comps):
            n = comps[1]
            return (
                comps[0] / jnp.where(n == 0, 1, n).astype(jnp.float64),
                n > 0,
            )

        return DeviceAgg(
            components=(
                AggComponent("add", "float64", 0.0),
                AggComponent("add", "int64", 0),
            ),
            contribs=contribs,
            finalize=finalize,
            result_type=T.DOUBLE,
        )
    if kind == "stddev":
        # (sum, sumsq, n); result() per _stddev_samp/_stddev_pop in
        # functions/udafs.py
        pop = fname.upper() == "STDDEV_POP"

        def contribs(args, act, seq=None):
            ok = act & args[0].valid
            x = jnp.where(ok, args[0].data.astype(jnp.float64), 0.0)
            return [x, x * x, ok.astype(jnp.int64)]

        def finalize(comps):
            s, ss, n = comps
            nf = n.astype(jnp.float64)
            mean_sq = s * s / jnp.where(n == 0, 1.0, nf)
            if pop:
                var = (ss - mean_sq) / jnp.where(n == 0, 1.0, nf)
                out = jnp.sqrt(jnp.maximum(var, 0.0))
                return out, n >= 1
            var = (ss - mean_sq) / jnp.where(n < 2, 1.0, nf - 1.0)
            out = jnp.sqrt(jnp.maximum(var, 0.0))
            out = jnp.where(n == 1, 0.0, out)
            return out, n >= 1

        return DeviceAgg(
            components=(
                AggComponent("add", "float64", 0.0),
                AggComponent("add", "float64", 0.0),
                AggComponent("add", "int64", 0),
            ),
            contribs=contribs,
            finalize=finalize,
            result_type=T.DOUBLE,
        )
    if kind == "correlation":
        def contribs(args, act, seq=None):
            ok = act & args[0].valid & args[1].valid
            x = jnp.where(ok, args[0].data.astype(jnp.float64), 0.0)
            y = jnp.where(ok, args[1].data.astype(jnp.float64), 0.0)
            return [ok.astype(jnp.int64), x, y, x * x, y * y, x * y]

        def finalize(comps):
            n, sx, sy, sxx, syy, sxy = comps
            nf = jnp.where(n == 0, 1.0, n.astype(jnp.float64))
            cov = sxy - sx * sy / nf
            vx = sxx - sx * sx / nf
            vy = syy - sy * sy / nf
            denom = jnp.sqrt(jnp.maximum(vx * vy, 0.0))
            out = jnp.where(denom > 0, cov / jnp.where(denom == 0, 1.0, denom), jnp.nan)
            return out, n > 0

        return DeviceAgg(
            components=tuple(
                AggComponent("add", "int64" if i == 0 else "float64", 0)
                for i in range(6)
            ),
            contribs=contribs,
            finalize=finalize,
            result_type=T.DOUBLE,
        )
    if kind in ("latest", "earliest"):
        # EARLIEST/LATEST_BY_OFFSET: argmin/argmax over a global arrival
        # sequence.  Component 0 orders (min/max-combined); the value/valid
        # components are 'argset': scatter_combine writes them from the row
        # that won component 0 (unique sequence numbers -> no ties).
        t = arg_types[0]
        if t.base in (SqlBaseType.ARRAY, SqlBaseType.MAP, SqlBaseType.STRUCT):
            raise DeviceUnsupported(f"{kind} over nested types on device")
        hashed = t.base in (SqlBaseType.STRING, SqlBaseType.BYTES)
        if t.base in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
            vdt = np.float64
        elif hashed or t.base != SqlBaseType.INTEGER:
            vdt = np.int64
        else:
            vdt = np.int32
        combine = "min" if kind == "earliest" else "max"
        init = _I64_MAX if kind == "earliest" else -_I64_MAX - 1

        def contribs(args, act, seq=None):
            v = args[0]
            if len(args) > 1:
                ignore_nulls = args[1].data.astype(bool)
            else:
                ignore_nulls = jnp.ones_like(act)
            cand = act & (v.valid | ~ignore_nulls)
            return [
                jnp.where(cand, seq, init),
                jnp.where(cand, v.data, 0).astype(vdt),
                (cand & v.valid).astype(np.int32),
            ]

        def finalize(comps):
            present = comps[0] != init
            return comps[1], present & (comps[2] != 0)

        return DeviceAgg(
            components=(
                AggComponent(combine, "int64", init),
                AggComponent("argset", np.dtype(vdt).name, 0),
                AggComponent("argset", "int32", 0),
            ),
            contribs=contribs,
            finalize=finalize,
            result_type=t,
        )
    if kind == "collect":
        # COLLECT_LIST / COLLECT_SET / EARLIEST_BY_OFFSET(n) /
        # LATEST_BY_OFFSET(n): bounded per-key vector state
        # (CollectListUdaf LIMIT cap; ring buffer for latest-N)
        # nested element types (ARRAY/MAP/STRUCT) ride as opaque int64
        # dictionary codes, exactly like strings — collect state stores the
        # codes and emission decodes elements through the dictionary
        t = arg_types[0]
        fn = fname.upper()
        ignore_nulls = True
        if fn == "COLLECT_LIST":
            from ksql_tpu.functions.udafs import _limit_of

            K, mode, collect_nulls = _limit_of("collect_list"), "append", True
        elif fn == "COLLECT_SET":
            from ksql_tpu.functions.udafs import _limit_of

            K, mode, collect_nulls = _limit_of("collect_set"), "set", True
        elif fn in ("EARLIEST_BY_OFFSET", "LATEST_BY_OFFSET"):
            K = literals[0] if literals else None
            mode = "append" if fn.startswith("EARLIEST") else "ring"
            collect_nulls = False
            if len(literals) > 1 and literals[1] is not None:
                ignore_nulls = bool(literals[1])
            elif len(literals) > 1:
                raise DeviceUnsupported(f"{fname} dynamic ignoreNulls on device")
        else:
            raise DeviceUnsupported(f"{fname} on device")
        if not isinstance(K, int) or K <= 0 or K > MAX_VEC_WIDTH:
            raise DeviceUnsupported(f"{fname} cap {K!r} on device")
        vdt = _vec_dtype(t)

        def contribs(args, act, seq=None):
            v = args[0]
            if collect_nulls:
                cand = act
            elif ignore_nulls:
                cand = act & v.valid
            else:
                cand = act
            return [
                cand.astype(jnp.int64),
                jnp.where(cand & v.valid, v.data, 0).astype(vdt),
                (cand & v.valid).astype(jnp.int8),
            ]

        ring = mode == "ring"
        undo_contribs = None
        if fn == "COLLECT_LIST":
            # table-aggregation undo: negative head removes the first
            # stored occurrence (_vec_remove; CollectListUdaf.undo)
            def undo_contribs(args, act, seq=None):
                v = args[0]
                return [
                    -act.astype(jnp.int64),
                    jnp.where(act & v.valid, v.data, 0).astype(vdt),
                    (act & v.valid).astype(jnp.int8),
                ]

        def finalize(comps):
            count, data, vbits = comps
            n = count.shape[0]
            if ring:
                start = jnp.where(count > K, count % K, 0).astype(jnp.int32)
                idx = (start[:, None] + jnp.arange(K, dtype=jnp.int32)) % K
                data = jnp.take_along_axis(data, idx, axis=1)
                vbits = jnp.take_along_axis(vbits, idx, axis=1)
            cnt = jnp.minimum(count, K).astype(jnp.int32)
            present = jnp.arange(K, dtype=jnp.int32)[None, :] < cnt[:, None]
            return data, present, (vbits != 0) & present

        return DeviceAgg(
            components=(
                AggComponent("vec_count", "int64", 0),
                AggComponent("vec_data", np.dtype(vdt).name, 0, width=K, mode=mode),
                AggComponent("vec_valid", "int8", 0, width=K),
            ),
            contribs=contribs,
            finalize=finalize,
            undo_contribs=undo_contribs,
            result_type=result_type,
        )
    if kind == "topk":
        # TOPK / TOPKDISTINCT over numerics/temporals: width-k sorted state
        t = arg_types[0]
        if t.base in (SqlBaseType.STRING, SqlBaseType.BYTES):
            raise DeviceUnsupported("string ordering on device")
        if t.base in (SqlBaseType.ARRAY, SqlBaseType.MAP, SqlBaseType.STRUCT):
            raise DeviceUnsupported(f"{fname} over nested types on device")
        k = literals[0] if literals else None
        if not isinstance(k, int) or k <= 0 or k > 256:
            raise DeviceUnsupported(f"{fname} k {k!r} on device")
        vdt = _vec_dtype(t)
        if vdt == np.float64:
            sentinel: object = -np.inf
        else:
            sentinel = np.iinfo(vdt).min

        distinct = fname.upper() == "TOPKDISTINCT"

        def tk_contribs(args, act, seq=None):
            v = args[0]
            ok = act & v.valid
            return [
                ok.astype(jnp.int32),
                jnp.where(ok, v.data, sentinel).astype(vdt),
            ]

        def tk_finalize(comps):
            count, data = comps
            if distinct:
                # distinct count isn't tracked; dtype-floor values (-inf /
                # INT_MIN) read as absent — the one documented parity edge
                present = data != jnp.asarray(sentinel, data.dtype)
            else:
                cnt = jnp.minimum(count, k).astype(jnp.int32)
                present = (
                    jnp.arange(k, dtype=jnp.int32)[None, :] < cnt[:, None]
                )
            return data, present, present

        return DeviceAgg(
            components=(
                AggComponent("add", "int32", 0),
                AggComponent(
                    "topk", np.dtype(vdt).name, sentinel, width=k,
                    mode="distinct" if distinct else "",
                ),
            ),
            contribs=tk_contribs,
            finalize=tk_finalize,
            result_type=result_type,
        )
    if kind in ("histogram", "attr"):
        # HISTOGRAM(string) -> MAP<STRING, BIGINT>: per-slot (value-code,
        # count) pairs.  Distinct values append set-style (capped at the
        # reference's 1000 entries — HistogramUdaf); every occurrence
        # scatter-adds ±1 to its element count, so the fold is invertible
        # and table-aggregation undo works by decrement (zero-count entries
        # read as absent, matching the oracle's _hist_undo deletion).
        t = arg_types[0]
        is_attr = kind == "attr"
        f64_repr = t.base in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL)
        K = 1000

        def _code64(v):
            if f64_repr:  # bitcast keeps doubles exact in the code column
                import jax

                return jax.lax.bitcast_convert_type(
                    v.data.astype(jnp.float64), jnp.int64
                )
            return v.data.astype(jnp.int64)

        def h_contribs(args, act, seq=None, sign=1):
            v = args[0]
            # histogram skips null values (_hist_acc); ATTR counts them as
            # an entry (Attr.java update with a null VALUE)
            cand = act if is_attr else act & v.valid
            head = jnp.where(cand, sign, 0).astype(jnp.int64)
            return [
                head,
                jnp.where(cand & v.valid, _code64(v), 0),
                (cand & v.valid).astype(jnp.int8),
                head,  # per-element count increment (carries the sign)
            ]

        def h_finalize(comps):
            cnt, data, vbits, nums = comps
            live = (
                jnp.arange(K, dtype=jnp.int32)[None, :]
                < jnp.minimum(cnt, K).astype(jnp.int32)[:, None]
            ) & (nums > 0)
            if is_attr:
                # the singleton entry's value, NULL when 0 or 2+ distinct
                # values are live (Attr.java map())
                n_live = jnp.sum(live, axis=1)
                pick = jnp.argmax(live, axis=1)
                rows = jnp.arange(cnt.shape[0])
                val = data[rows, pick]
                if f64_repr:
                    import jax

                    val = jax.lax.bitcast_convert_type(val, jnp.float64)
                valid = (n_live == 1) & (vbits[rows, pick] != 0)
                return val, valid
            ones = jnp.ones(cnt.shape[0], bool)
            return data, ones, live, nums

        return DeviceAgg(
            components=(
                AggComponent("vec_count", "int64", 0, mode="hist"),
                AggComponent("vec_data", "int64", 0, width=K, mode="hist"),
                AggComponent("vec_valid", "int8", 0, width=K),
                AggComponent("hist_count", "int64", 0, width=K),
            ),
            contribs=h_contribs,
            finalize=h_finalize,
            result_type=result_type,
            undo_contribs=lambda args, act, seq=None: h_contribs(
                args, act, seq, sign=-1
            ),
        )
    if kind == "collect_all_valid":
        # GenericVarArgUdaf/ObjVarColArgUdaf: append the FIRST argument's
        # value when EVERY argument (incl. variadic) is non-null
        t = arg_types[0]
        K = 1000
        vdt = _vec_dtype(t)

        def cav_contribs(args, act, seq=None):
            v = args[0]
            cand = act
            for a in args:
                cand = cand & a.valid
            return [
                cand.astype(jnp.int64),
                jnp.where(cand, v.data, 0).astype(vdt),
                cand.astype(jnp.int8),
            ]

        def cav_finalize(comps):
            count, data, vbits = comps
            cnt = jnp.minimum(count, K).astype(jnp.int32)
            present = jnp.arange(K, dtype=jnp.int32)[None, :] < cnt[:, None]
            return data, present, (vbits != 0) & present

        return DeviceAgg(
            components=(
                AggComponent("vec_count", "int64", 0),
                AggComponent("vec_data", np.dtype(vdt).name, 0, width=K, mode="append"),
                AggComponent("vec_valid", "int8", 0, width=K),
            ),
            contribs=cav_contribs,
            finalize=cav_finalize,
            result_type=result_type,
        )
    raise DeviceUnsupported(f"aggregate kind {kind} on device")
