"""Recursive-descent SQL parser.

Statement coverage mirrors the reference grammar's statement list
(ksqldb-parser/src/main/antlr4/.../SqlBase.g4:47-128): CREATE
STREAM/TABLE [AS SELECT], INSERT INTO/VALUES, SELECT with
WINDOW/WHERE/GROUP BY/PARTITION BY/HAVING/EMIT/LIMIT, joins with WITHIN,
DROP, LIST/SHOW, DESCRIBE, EXPLAIN, TERMINATE/PAUSE/RESUME, SET/UNSET,
DEFINE/UNDEFINE, CREATE TYPE, connector DDL, PRINT, RUN SCRIPT, ASSERT.

Expression grammar (SqlBase.g4:281-351) is precedence-climbing:
OR < AND < NOT < predicates (comparison, BETWEEN, IN, LIKE, IS NULL,
IS DISTINCT FROM) < additive < multiplicative < unary < postfix
(subscript, struct dereference) < primary.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ksql_tpu.common.errors import ParsingException
from ksql_tpu.common.types import SqlType, parse_type_name
from ksql_tpu.execution import expressions as ex
from ksql_tpu.parser import ast_nodes as ast
from ksql_tpu.parser.lexer import Token, TokType, tokenize

_UNITS_MS = {
    "MILLISECOND": 1,
    "MILLISECONDS": 1,
    "SECOND": 1000,
    "SECONDS": 1000,
    "MINUTE": 60_000,
    "MINUTES": 60_000,
    "HOUR": 3_600_000,
    "HOURS": 3_600_000,
    "DAY": 86_400_000,
    "DAYS": 86_400_000,
}

# Words that terminate an aliased relation (cannot be an implicit alias).
_RESERVED_AFTER_RELATION = {
    "WINDOW", "WHERE", "GROUP", "PARTITION", "HAVING", "EMIT", "LIMIT",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "ON", "WITHIN",
    "AND", "OR", "NOT", "AS", "EOF",
}


class Parser:
    def __init__(
        self,
        sql: str,
        variables: Optional[Dict[str, str]] = None,
        type_registry: Optional[Dict[str, SqlType]] = None,
    ):
        if variables:
            sql = substitute_variables(sql, variables)
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0
        self.type_registry = type_registry or {}

    # ------------------------------------------------------------- plumbing
    def peek(self, offset: int = 0) -> Token:
        j = min(self.i + offset, len(self.tokens) - 1)
        return self.tokens[j]

    def next(self) -> Token:
        t = self.tokens[self.i]
        if t.type != TokType.EOF:
            self.i += 1
        return t

    def err(self, msg: str, tok: Optional[Token] = None):
        t = tok or self.peek()
        raise ParsingException(f"{msg} (got {t.type} {t.text!r})", t.line, t.col)

    def at_kw(self, *words: str) -> bool:
        for off, w in enumerate(words):
            t = self.peek(off)
            if t.type != TokType.IDENT or t.text != w:
                return False
        return True

    def accept_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.i += len(words)
            return True
        return False

    def expect_kw(self, *words: str):
        if not self.accept_kw(*words):
            self.err(f"expected {' '.join(words)}")

    def at_op(self, op: str) -> bool:
        t = self.peek()
        return t.type == TokType.OP and t.text == op

    def accept_op(self, op: str) -> bool:
        if self.at_op(op):
            self.i += 1
            return True
        return False

    def expect_op(self, op: str):
        if not self.accept_op(op):
            self.err(f"expected {op!r}")

    def identifier(self) -> str:
        t = self.peek()
        if t.type in (TokType.IDENT, TokType.QIDENT):
            self.next()
            return t.text
        self.err("expected identifier")

    # ----------------------------------------------------------- statements
    def parse_statements(self) -> List[ast.PreparedStatement]:
        out: List[ast.PreparedStatement] = []
        while self.peek().type != TokType.EOF:
            if self.accept_op(";"):
                continue
            start_i = self.i
            stmt = self.parse_statement()
            text = self._statement_text(start_i)
            out.append(ast.PreparedStatement(text=text, statement=stmt))
            if self.peek().type != TokType.EOF:
                self.expect_op(";")
        return out

    def _statement_text(self, start_i: int) -> str:
        parts = []
        for t in self.tokens[start_i : self.i]:
            if t.type == TokType.STRING:
                parts.append("'" + t.text.replace("'", "''") + "'")
            elif t.type == TokType.QIDENT:
                parts.append("`" + t.text + "`")
            elif t.type == TokType.VARIABLE:
                parts.append("${" + t.text + "}")
            else:
                parts.append(t.text)
        return " ".join(parts)

    def parse_statement(self) -> ast.Statement:
        if self.at_kw("SELECT"):
            return self.parse_query()
        if self.at_kw("CREATE"):
            return self.parse_create()
        if self.at_kw("INSERT"):
            return self.parse_insert()
        if self.at_kw("DROP"):
            return self.parse_drop()
        if self.at_kw("LIST") or self.at_kw("SHOW"):
            return self.parse_list()
        if self.at_kw("DESCRIBE"):
            return self.parse_describe()
        if self.at_kw("EXPLAIN"):
            self.next()
            analyze = self.accept_kw("ANALYZE")
            if self.peek().type in (TokType.IDENT, TokType.QIDENT) and not self._starts_statement():
                return ast.Explain(query_id=self.identifier(), analyze=analyze)
            return ast.Explain(statement=self.parse_statement(), analyze=analyze)
        if self.accept_kw("TERMINATE"):
            if self.accept_kw("ALL"):
                return ast.TerminateQuery(query_id=None)
            return ast.TerminateQuery(query_id=self.identifier())
        if self.accept_kw("PAUSE"):
            if self.accept_kw("ALL"):
                return ast.PauseQuery(query_id=None)
            return ast.PauseQuery(query_id=self.identifier())
        if self.accept_kw("RESUME"):
            if self.accept_kw("ALL"):
                return ast.ResumeQuery(query_id=None)
            return ast.ResumeQuery(query_id=self.identifier())
        if self.accept_kw("SET"):
            name = self._property_name_token()
            self.expect_op("=")
            return ast.SetProperty(name=name, value=self._string_literal())
        if self.accept_kw("UNSET"):
            return ast.UnsetProperty(name=self._property_name_token())
        if self.accept_kw("ALTER", "SYSTEM"):
            name = self._property_name_token()
            self.expect_op("=")
            return ast.AlterSystemProperty(name=name, value=self._string_literal())
        if self.at_kw("ALTER"):
            return self.parse_alter_source()
        if self.accept_kw("DEFINE"):
            name = self.identifier()
            self.expect_op("=")
            return ast.DefineVariable(name=name, value=self._string_literal())
        if self.accept_kw("UNDEFINE"):
            return ast.UndefineVariable(name=self.identifier())
        if self.accept_kw("RUN", "SCRIPT"):
            return ast.RunScript(path=self._string_literal())
        if self.accept_kw("PRINT"):
            return self.parse_print()
        if self.at_kw("ASSERT"):
            return self.parse_assert()
        self.err("unknown statement")

    def _starts_statement(self) -> bool:
        return self.at_kw("SELECT") or self.at_kw("CREATE") or self.at_kw("INSERT")

    def _string_literal(self) -> str:
        t = self.peek()
        if t.type == TokType.STRING:
            self.next()
            return t.text
        self.err("expected string literal")

    def _property_name_token(self) -> str:
        """Config-key position: quoted string, or unquoted dotted identifiers
        (config keys are canonically lower-case)."""
        t = self.peek()
        if t.type == TokType.STRING:
            self.next()
            return t.text
        if t.type in (TokType.IDENT, TokType.QIDENT):
            parts = [self.identifier()]
            while self.accept_op("."):
                parts.append(self.identifier())
            return ".".join(p.lower() for p in parts)
        self.err("expected property name")

    def _integer_token(self) -> int:
        t = self.next()
        if t.type != TokType.INTEGER:
            self.err("expected integer", t)
        return int(t.text)

    # ----------------------------------------------------------------- query
    def parse_query(self) -> ast.Query:
        self.expect_kw("SELECT")
        items: List[Any] = []
        while True:
            items.append(self.parse_select_item())
            if not self.accept_op(","):
                break
        self.expect_kw("FROM")
        relation = self.parse_relation()
        window = None
        if self.accept_kw("WINDOW"):
            window = self.parse_window()
        where = self.parse_expression() if self.accept_kw("WHERE") else None
        group_by: Tuple[ex.Expression, ...] = ()
        if self.accept_kw("GROUP", "BY"):
            group_by = tuple(self._grouping_list())
        partition_by: Tuple[ex.Expression, ...] = ()
        if self.accept_kw("PARTITION", "BY"):
            partition_by = tuple(self._expression_list())
        having = self.parse_expression() if self.accept_kw("HAVING") else None
        refinement = None
        if self.accept_kw("EMIT", "CHANGES"):
            refinement = ast.Refinement(ast.RefinementType.CHANGES)
        elif self.accept_kw("EMIT", "FINAL"):
            refinement = ast.Refinement(ast.RefinementType.FINAL)
        limit = None
        if self.accept_kw("LIMIT"):
            t = self.next()
            if t.type != TokType.INTEGER:
                self.err("expected integer after LIMIT", t)
            limit = int(t.text)
        return ast.Query(
            select=ast.Select(items=tuple(items)),
            from_=relation,
            window=window,
            where=where,
            group_by=group_by,
            partition_by=partition_by,
            having=having,
            refinement=refinement,
            limit=limit,
        )

    def _expression_list(self) -> List[ex.Expression]:
        out = [self.parse_expression()]
        while self.accept_op(","):
            out.append(self.parse_expression())
        return out

    def _grouping_list(self) -> List[ex.Expression]:
        """GROUP BY elements; `(a, b)` tuples flatten into the grouping list
        (SqlBase.g4 groupBy -> groupingExpressions)."""
        out: List[ex.Expression] = []
        while True:
            if self.at_op("("):
                save = self.i
                self.next()
                try:
                    inner = [self.parse_expression()]
                    while self.accept_op(","):
                        inner.append(self.parse_expression())
                    if self.accept_op(")") and len(inner) > 1:
                        out.extend(inner)
                        if not self.accept_op(","):
                            break
                        continue
                except ParsingException:
                    pass
                self.i = save
            out.append(self.parse_expression())
            if not self.accept_op(","):
                break
        return out

    def parse_select_item(self):
        if self.accept_op("*"):
            return ast.AllColumns()
        # qualified star: src.*
        if (
            self.peek().type in (TokType.IDENT, TokType.QIDENT)
            and self.peek(1).type == TokType.OP
            and self.peek(1).text == "."
            and self.peek(2).type == TokType.OP
            and self.peek(2).text == "*"
        ):
            src = self.identifier()
            self.next()
            self.next()
            return ast.AllColumns(source=src)
        expr = self.parse_expression()
        alias = None
        if self.accept_kw("AS"):
            alias = self.identifier()
        elif self.peek().type in (TokType.IDENT, TokType.QIDENT) and (
            self.peek().type == TokType.QIDENT
            or self.peek().text not in _RESERVED_AFTER_RELATION | {"FROM"}
        ):
            alias = self.identifier()
        return ast.SingleColumn(expression=expr, alias=alias)

    # -------------------------------------------------------------- relation
    def parse_relation(self) -> ast.Relation:
        left = self.parse_aliased_relation()
        while True:
            jt = self._join_type()
            if jt is None:
                return left
            right = self.parse_aliased_relation()
            within = None
            if self.accept_kw("WITHIN"):
                within = self.parse_within()
            self.expect_kw("ON")  # joinCriteria is mandatory (SqlBase.g4:242)
            criteria = ast.JoinOn(expression=self.parse_expression())
            left = ast.Join(
                join_type=jt, left=left, right=right, criteria=criteria, within=within
            )

    def _join_type(self) -> Optional[ast.JoinType]:
        if self.accept_kw("INNER", "JOIN") or self.accept_kw("JOIN"):
            return ast.JoinType.INNER
        if self.accept_kw("LEFT", "OUTER", "JOIN") or self.accept_kw("LEFT", "JOIN"):
            return ast.JoinType.LEFT
        if self.accept_kw("RIGHT", "OUTER", "JOIN") or self.accept_kw("RIGHT", "JOIN"):
            return ast.JoinType.RIGHT
        if self.accept_kw("FULL", "OUTER", "JOIN") or self.accept_kw("FULL", "JOIN") or self.accept_kw("OUTER", "JOIN"):
            return ast.JoinType.OUTER
        return None

    def parse_aliased_relation(self) -> ast.Relation:
        name = self.identifier()
        rel: ast.Relation = ast.Table(name=name)
        if self.accept_kw("AS"):
            return ast.AliasedRelation(relation=rel, alias=self.identifier())
        t = self.peek()
        if t.type == TokType.QIDENT or (
            t.type == TokType.IDENT and t.text not in _RESERVED_AFTER_RELATION
        ):
            return ast.AliasedRelation(relation=rel, alias=self.identifier())
        return rel

    def parse_within(self) -> ast.WithinExpression:
        if self.accept_op("("):
            before = self.parse_duration_ms()
            self.expect_op(",")
            after = self.parse_duration_ms()
            self.expect_op(")")
        else:
            before = after = self.parse_duration_ms()
        grace = None
        if self.accept_kw("GRACE", "PERIOD"):
            grace = self.parse_duration_ms()
        return ast.WithinExpression(before_ms=before, after_ms=after, grace_ms=grace)

    def parse_duration_ms(self) -> int:
        t = self.next()
        if t.type != TokType.INTEGER:
            self.err("expected duration value", t)
        unit_tok = self.next()
        unit = unit_tok.text
        if unit_tok.type != TokType.IDENT or unit not in _UNITS_MS:
            self.err(f"expected time unit, got {unit!r}", unit_tok)
        return int(t.text) * _UNITS_MS[unit]

    # ---------------------------------------------------------------- window
    def parse_window(self) -> ast.WindowExpression:
        # optional window name (legacy): IDENT before type keyword
        if (
            self.peek().type == TokType.IDENT
            and self.peek().text not in ("TUMBLING", "HOPPING", "SESSION")
            and self.peek(1).type == TokType.IDENT
            and self.peek(1).text in ("TUMBLING", "HOPPING", "SESSION")
        ):
            self.next()
        kind = self.next().text
        self.expect_op("(")
        size_ms = advance_ms = gap_ms = retention_ms = grace_ms = None
        if kind == "TUMBLING":
            wt = ast.WindowType.TUMBLING
            self.expect_kw("SIZE")
            size_ms = self.parse_duration_ms()
        elif kind == "HOPPING":
            wt = ast.WindowType.HOPPING
            self.expect_kw("SIZE")
            size_ms = self.parse_duration_ms()
            self.expect_op(",")
            self.expect_kw("ADVANCE", "BY")
            advance_ms = self.parse_duration_ms()
        elif kind == "SESSION":
            wt = ast.WindowType.SESSION
            gap_ms = self.parse_duration_ms()
        else:
            self.err(f"unknown window type {kind}")
        while self.accept_op(","):
            if self.accept_kw("RETENTION"):
                retention_ms = self.parse_duration_ms()
            elif self.accept_kw("GRACE", "PERIOD"):
                grace_ms = self.parse_duration_ms()
            else:
                self.err("expected RETENTION or GRACE PERIOD")
        self.expect_op(")")
        return ast.WindowExpression(
            window_type=wt,
            size_ms=size_ms,
            advance_ms=advance_ms,
            gap_ms=gap_ms,
            retention_ms=retention_ms,
            grace_ms=grace_ms,
        )

    # ------------------------------------------------------------------- DDL
    def parse_create(self) -> ast.Statement:
        self.expect_kw("CREATE")
        or_replace = bool(self.accept_kw("OR", "REPLACE"))
        is_source = bool(self.accept_kw("SOURCE"))
        if self.accept_kw("SINK", "CONNECTOR") :
            return self._create_connector("SINK")
        if is_source and self.at_kw("CONNECTOR"):
            self.expect_kw("CONNECTOR")
            return self._create_connector("SOURCE")
        if self.accept_kw("TYPE"):
            if_not_exists = bool(self.accept_kw("IF", "NOT", "EXISTS"))
            name = self.identifier()
            self.expect_kw("AS")
            return ast.RegisterType(name=name, type=self.parse_type(), if_not_exists=if_not_exists)
        is_table = False
        if self.accept_kw("TABLE"):
            is_table = True
        else:
            self.expect_kw("STREAM")
        if_not_exists = bool(self.accept_kw("IF", "NOT", "EXISTS"))
        name = self.identifier()
        elements: Tuple[ast.TableElement, ...] = ()
        if self.at_op("("):
            elements = tuple(self.parse_table_elements())
        props: Dict[str, Any] = {}
        if self.accept_kw("WITH"):
            props = self.parse_properties()
        if self.accept_kw("AS"):
            query = self.parse_query()
            if is_table:
                return ast.CreateTableAsSelect(
                    name=name, query=query, properties=props,
                    if_not_exists=if_not_exists, or_replace=or_replace,
                )
            return ast.CreateStreamAsSelect(
                name=name, query=query, properties=props,
                if_not_exists=if_not_exists, or_replace=or_replace,
            )
        cls = ast.CreateTable if is_table else ast.CreateStream
        return cls(
            name=name, elements=elements, properties=props,
            if_not_exists=if_not_exists, or_replace=or_replace, is_source=is_source,
        )

    def _create_connector(self, ctype: str) -> ast.CreateConnector:
        if_not_exists = bool(self.accept_kw("IF", "NOT", "EXISTS"))
        name = self.identifier()
        self.expect_kw("WITH")
        return ast.CreateConnector(
            name=name, properties=self.parse_properties(normalize_keys=False),
            connector_type=ctype, if_not_exists=if_not_exists,
        )

    def parse_table_elements(self) -> List[ast.TableElement]:
        self.expect_op("(")
        out: List[ast.TableElement] = []
        while True:
            name = self.identifier()
            t = self.parse_type()
            constraint = ast.ColumnConstraint.NONE
            header_key = None
            if self.accept_kw("PRIMARY", "KEY"):
                constraint = ast.ColumnConstraint.PRIMARY_KEY
            elif self.accept_kw("KEY"):
                constraint = ast.ColumnConstraint.KEY
            elif self.accept_kw("HEADERS"):
                constraint = ast.ColumnConstraint.HEADERS
            elif self.accept_kw("HEADER"):
                self.expect_op("(")
                header_key = self._string_literal()
                self.expect_op(")")
                constraint = ast.ColumnConstraint.HEADERS
            out.append(
                ast.TableElement(name=name, type=t, constraint=constraint, header_key=header_key)
            )
            if not self.accept_op(","):
                break
        self.expect_op(")")
        return out

    def parse_properties(self, normalize_keys: bool = True) -> Dict[str, Any]:
        """WITH (...) property map.  Source DDL property names are
        case-insensitive (normalized to upper); connector configs are
        case-sensitive Kafka Connect keys, so quoted keys stay verbatim
        (normalize_keys=False)."""
        self.expect_op("(")
        props: Dict[str, Any] = {}
        if not self.at_op(")"):
            while True:
                t = self.peek()
                if t.type == TokType.STRING:
                    key = self.next().text
                    if normalize_keys:
                        key = key.upper()
                else:
                    key = self.identifier().upper()
                self.expect_op("=")
                props[key] = self._property_value()
                if not self.accept_op(","):
                    break
        self.expect_op(")")
        return props

    def _property_value(self) -> Any:
        t = self.peek()
        if t.type == TokType.STRING:
            self.next()
            return t.text
        if t.type == TokType.INTEGER:
            self.next()
            return int(t.text)
        if t.type in (TokType.DECIMAL, TokType.FLOAT):
            self.next()
            return float(t.text)
        if t.type == TokType.IDENT and t.text in ("TRUE", "FALSE"):
            self.next()
            return t.text == "TRUE"
        if self.accept_op("-"):
            v = self._property_value()
            return -v
        if t.type == TokType.IDENT:  # bare identifier value
            self.next()
            return t.text
        self.err("expected property value")

    def parse_insert(self) -> ast.Statement:
        self.expect_kw("INSERT", "INTO")
        target = self.identifier()
        if self.at_kw("SELECT"):
            return ast.InsertInto(target=target, query=self.parse_query())
        columns: Tuple[str, ...] = ()
        if self.at_op("("):
            self.expect_op("(")
            cols = [self.identifier()]
            while self.accept_op(","):
                cols.append(self.identifier())
            self.expect_op(")")
            columns = tuple(cols)
        self.expect_kw("VALUES")
        self.expect_op("(")
        values = [self.parse_expression()]
        while self.accept_op(","):
            values.append(self.parse_expression())
        self.expect_op(")")
        return ast.InsertValues(target=target, columns=columns, values=tuple(values))

    def parse_drop(self) -> ast.Statement:
        self.expect_kw("DROP")
        if self.accept_kw("CONNECTOR"):
            if_exists = bool(self.accept_kw("IF", "EXISTS"))
            return ast.DropConnector(name=self.identifier(), if_exists=if_exists)
        if self.accept_kw("TYPE"):
            if_exists = bool(self.accept_kw("IF", "EXISTS"))
            return ast.DropType(name=self.identifier(), if_exists=if_exists)
        is_table = bool(self.accept_kw("TABLE"))
        if not is_table:
            self.expect_kw("STREAM")
        if_exists = bool(self.accept_kw("IF", "EXISTS"))
        name = self.identifier()
        delete_topic = bool(self.accept_kw("DELETE", "TOPIC"))
        return ast.DropSource(
            name=name, is_table=is_table, if_exists=if_exists, delete_topic=delete_topic
        )

    def parse_alter_source(self) -> ast.Statement:
        self.expect_kw("ALTER")
        is_table = bool(self.accept_kw("TABLE"))
        if not is_table:
            self.expect_kw("STREAM")
        name = self.identifier()
        cols: List[ast.TableElement] = []
        while True:
            self.expect_kw("ADD")
            self.accept_kw("COLUMN")
            cname = self.identifier()
            cols.append(ast.TableElement(name=cname, type=self.parse_type()))
            if not self.accept_op(","):
                break
        return ast.AlterSource(name=name, is_table=is_table, new_columns=tuple(cols))

    def parse_list(self) -> ast.Statement:
        self.next()  # LIST | SHOW
        if self.accept_kw("STREAMS"):
            return ast.ListStreams(extended=bool(self.accept_kw("EXTENDED")))
        if self.accept_kw("TABLES"):
            return ast.ListTables(extended=bool(self.accept_kw("EXTENDED")))
        if self.accept_kw("ALL", "TOPICS"):
            return ast.ListTopics(show_all=True, extended=bool(self.accept_kw("EXTENDED")))
        if self.accept_kw("TOPICS"):
            return ast.ListTopics(extended=bool(self.accept_kw("EXTENDED")))
        if self.accept_kw("QUERIES"):
            return ast.ListQueries(extended=bool(self.accept_kw("EXTENDED")))
        if self.accept_kw("PROPERTIES"):
            return ast.ListProperties()
        if self.accept_kw("FUNCTIONS"):
            return ast.ListFunctions()
        if self.accept_kw("TYPES"):
            return ast.ListTypes()
        if self.accept_kw("VARIABLES"):
            return ast.ListVariables()
        if self.accept_kw("CONNECTORS"):
            return ast.ListConnectors()
        if self.accept_kw("SOURCE", "CONNECTORS"):
            return ast.ListConnectors(scope="SOURCE")
        if self.accept_kw("SINK", "CONNECTORS"):
            return ast.ListConnectors(scope="SINK")
        if self.accept_kw("COLUMNS", "FROM"):
            src = self.identifier()
            return ast.ShowColumns(source=src, extended=bool(self.accept_kw("EXTENDED")))
        self.err("unknown LIST/SHOW target")

    def parse_describe(self) -> ast.Statement:
        self.expect_kw("DESCRIBE")
        if self.accept_kw("FUNCTION"):
            return ast.DescribeFunction(name=self.identifier())
        if self.accept_kw("CONNECTOR"):
            return ast.DescribeConnector(name=self.identifier())
        if self.accept_kw("STREAMS"):
            return ast.DescribeStreams(extended=bool(self.accept_kw("EXTENDED")))
        if self.accept_kw("TABLES"):
            return ast.DescribeTables(extended=bool(self.accept_kw("EXTENDED")))
        source = self.identifier()
        return ast.ShowColumns(source=source, extended=bool(self.accept_kw("EXTENDED")))

    def parse_print(self) -> ast.Statement:
        t = self.peek()
        if t.type == TokType.STRING:
            topic = self.next().text
        elif t.type == TokType.IDENT:
            # topic names are case-sensitive; keep original spelling
            self.next()
            topic = t.raw or t.text
        else:
            topic = self.identifier()
        from_beginning = bool(self.accept_kw("FROM", "BEGINNING"))
        interval = None
        limit = None
        while True:
            if self.accept_kw("INTERVAL"):
                interval = self._integer_token()
            elif self.accept_kw("LIMIT"):
                limit = self._integer_token()
            else:
                break
        return ast.PrintTopic(
            topic=topic, from_beginning=from_beginning, interval=interval, limit=limit
        )

    def parse_assert(self) -> ast.Statement:
        self.expect_kw("ASSERT")
        if self.accept_kw("NULL", "VALUES") or self.accept_kw("TOMBSTONE"):
            source, cols, vals = self._assert_values_body(tombstone=True)
            return ast.AssertTombstone(source=source, columns=cols, values=vals)
        if self.accept_kw("VALUES"):
            source, cols, vals = self._assert_values_body()
            return ast.AssertValues(source=source, columns=cols, values=vals)
        if self.accept_kw("STREAM"):
            stmt = self._assert_source_body(is_table=False)
            return ast.AssertStream(statement=stmt)
        if self.accept_kw("TABLE"):
            stmt = self._assert_source_body(is_table=True)
            return ast.AssertTable(statement=stmt)
        self.err("expected VALUES, NULL VALUES, STREAM or TABLE after ASSERT")

    def _assert_values_body(self, tombstone: bool = False):
        source = self.identifier()
        cols: Tuple[str, ...] = ()
        if self.at_op("("):
            self.expect_op("(")
            c = [self.identifier()]
            while self.accept_op(","):
                c.append(self.identifier())
            self.expect_op(")")
            cols = tuple(c)
        # tombstone form: ASSERT NULL VALUES <source> (cols) KEY (vals)
        if tombstone:
            self.expect_kw("KEY")
        else:
            self.expect_kw("VALUES")
        self.expect_op("(")
        vals = [self.parse_expression()]
        while self.accept_op(","):
            vals.append(self.parse_expression())
        self.expect_op(")")
        return source, cols, tuple(vals)

    def _assert_source_body(self, is_table: bool):
        name = self.identifier()
        elements: Tuple[ast.TableElement, ...] = ()
        if self.at_op("("):
            elements = tuple(self.parse_table_elements())
        props: Dict[str, Any] = {}
        if self.accept_kw("WITH"):
            props = self.parse_properties()
        cls = ast.CreateTable if is_table else ast.CreateStream
        return cls(name=name, elements=elements, properties=props)

    # ------------------------------------------------------------------ types
    def parse_type(self) -> SqlType:
        name = self.identifier().upper()
        if name == "VARCHAR" and self.at_op("("):
            # legacy VARCHAR(STRING)
            self.next()
            self.expect_kw("STRING")
            self.expect_op(")")
            return parse_type_name("VARCHAR")
        if name == "DECIMAL":
            self.expect_op("(")
            p = self._integer_token()
            self.expect_op(",")
            s = self._integer_token()
            self.expect_op(")")
            return SqlType.decimal(p, s)
        if name == "ARRAY":
            self.expect_op("<")
            el = self.parse_type()
            self.expect_op(">")
            return SqlType.array(el)
        if name == "MAP":
            self.expect_op("<")
            k = self.parse_type()
            self.expect_op(",")
            v = self.parse_type()
            self.expect_op(">")
            return SqlType.map(k, v)
        if name == "STRUCT":
            self.expect_op("<")
            fields: List[Tuple[str, SqlType]] = []
            if not self.at_op(">"):
                while True:
                    fname = self.identifier()
                    fields.append((fname, self.parse_type()))
                    if not self.accept_op(","):
                        break
            self.expect_op(">")
            return SqlType.struct(fields)
        try:
            return parse_type_name(name)
        except ValueError:
            if name in self.type_registry:
                return self.type_registry[name]
            raise ParsingException(f"unknown type {name!r}") from None

    # ------------------------------------------------------------ expressions
    def parse_expression(self) -> ex.Expression:
        return self._parse_or()

    def _parse_or(self) -> ex.Expression:
        left = self._parse_and()
        while self.accept_kw("OR"):
            right = self._parse_and()
            left = ex.LogicalBinary(op=ex.LogicOp.OR, left=left, right=right)
        return left

    def _parse_and(self) -> ex.Expression:
        left = self._parse_not()
        while self.accept_kw("AND"):
            right = self._parse_not()
            left = ex.LogicalBinary(op=ex.LogicOp.AND, left=left, right=right)
        return left

    def _parse_not(self) -> ex.Expression:
        if self.accept_kw("NOT"):
            return ex.Not(operand=self._parse_not())
        return self._parse_predicate()

    _COMPARE = {
        "=": ex.CompareOp.EQ,
        "<>": ex.CompareOp.NEQ,
        "!=": ex.CompareOp.NEQ,
        "<": ex.CompareOp.LT,
        "<=": ex.CompareOp.LTE,
        ">": ex.CompareOp.GT,
        ">=": ex.CompareOp.GTE,
    }

    def _parse_predicate(self) -> ex.Expression:
        # at most one predicate per value expression
        # (SqlBase.g4:295 predicated : valueExpression predicate?)
        left = self._parse_additive()
        t = self.peek()
        if t.type == TokType.OP and t.text in self._COMPARE:
            self.next()
            return ex.Comparison(op=self._COMPARE[t.text], left=left,
                                 right=self._parse_additive())
        if self.at_kw("IS", "DISTINCT", "FROM"):
            self.i += 3
            return ex.Comparison(op=ex.CompareOp.IS_DISTINCT_FROM, left=left,
                                 right=self._parse_additive())
        if self.at_kw("IS", "NOT", "DISTINCT", "FROM"):
            self.i += 4
            return ex.Comparison(op=ex.CompareOp.IS_NOT_DISTINCT_FROM, left=left,
                                 right=self._parse_additive())
        if self.accept_kw("IS", "NOT", "NULL"):
            return ex.IsNotNull(operand=left)
        if self.accept_kw("IS", "NULL"):
            return ex.IsNull(operand=left)
        save = self.i
        negated = bool(self.accept_kw("NOT"))
        if self.accept_kw("BETWEEN"):
            lower = self._parse_additive()
            self.expect_kw("AND")
            upper = self._parse_additive()
            return ex.Between(value=left, lower=lower, upper=upper, negated=negated)
        if self.accept_kw("IN"):
            self.expect_op("(")
            items = [self.parse_expression()]
            while self.accept_op(","):
                items.append(self.parse_expression())
            self.expect_op(")")
            return ex.InList(value=left, items=tuple(items), negated=negated)
        if self.accept_kw("LIKE"):
            pattern = self._parse_additive()
            escape = None
            if self.accept_kw("ESCAPE"):
                escape = self._string_literal()
            return ex.Like(value=left, pattern=pattern, escape=escape, negated=negated)
        if negated:
            self.i = save
        return left

    def _parse_additive(self) -> ex.Expression:
        left = self._parse_multiplicative()
        while True:
            if self.accept_op("+"):
                left = ex.ArithmeticBinary(op=ex.ArithOp.ADD, left=left, right=self._parse_multiplicative())
            elif self.accept_op("-"):
                left = ex.ArithmeticBinary(op=ex.ArithOp.SUBTRACT, left=left, right=self._parse_multiplicative())
            else:
                return left

    def _parse_multiplicative(self) -> ex.Expression:
        left = self._parse_unary()
        while True:
            if self.accept_op("*"):
                left = ex.ArithmeticBinary(op=ex.ArithOp.MULTIPLY, left=left, right=self._parse_unary())
            elif self.accept_op("/"):
                left = ex.ArithmeticBinary(op=ex.ArithOp.DIVIDE, left=left, right=self._parse_unary())
            elif self.accept_op("%"):
                left = ex.ArithmeticBinary(op=ex.ArithOp.MODULUS, left=left, right=self._parse_unary())
            else:
                return left

    def _parse_unary(self) -> ex.Expression:
        if self.accept_op("-"):
            operand = self._parse_unary()
            if isinstance(operand, ex.IntegerLiteral):
                return ex.IntegerLiteral(value=-operand.value)
            if isinstance(operand, ex.LongLiteral):
                return ex.LongLiteral(value=-operand.value)
            if isinstance(operand, ex.DoubleLiteral):
                return ex.DoubleLiteral(value=-operand.value)
            if isinstance(operand, ex.DecimalLiteral):
                return ex.DecimalLiteral(text="-" + operand.text)
            return ex.ArithmeticUnary(op=ex.ArithOp.SUBTRACT, operand=operand)
        if self.accept_op("+"):
            return self._parse_unary()
        return self._parse_postfix()

    def _parse_postfix(self) -> ex.Expression:
        expr = self._parse_primary()
        while True:
            if self.accept_op("["):
                index = self.parse_expression()
                self.expect_op("]")
                expr = ex.Subscript(base=expr, index=index)
            elif self.peek().type == TokType.OP and self.peek().text == "->":
                self.next()
                if self.accept_op("*"):
                    return ex.StructAll(base=expr)
                expr = ex.Dereference(base=expr, field=self.identifier())
            else:
                return expr

    def _is_lambda_ahead(self) -> bool:
        """At '(': does '(' IDENT (',' IDENT)* ')' '=>' follow?"""
        j = self.i + 1
        toks = self.tokens
        while True:
            if toks[j].type not in (TokType.IDENT, TokType.QIDENT):
                return False
            j += 1
            if toks[j].type == TokType.OP and toks[j].text == ",":
                j += 1
                continue
            break
        if not (toks[j].type == TokType.OP and toks[j].text == ")"):
            return False
        j += 1
        return toks[j].type == TokType.OP and toks[j].text == "=>"

    def _parse_primary(self) -> ex.Expression:
        t = self.peek()
        # literals
        if t.type == TokType.STRING:
            self.next()
            return ex.StringLiteral(value=t.text)
        if t.type == TokType.INTEGER:
            self.next()
            v = int(t.text)
            if -(2**31) <= v < 2**31:
                return ex.IntegerLiteral(value=v)
            if not -(2**63) <= v < 2**63:
                # Java Long.parseLong overflow (AstBuilder literal handling)
                raise ParsingException(f"Invalid numeric literal: {t.text}", t.line, t.col)
            return ex.LongLiteral(value=v)
        if t.type == TokType.FLOAT:
            self.next()
            fv = float(t.text)
            if fv in (float("inf"), float("-inf")):
                raise ParsingException(f"Number overflows DOUBLE: {t.text}", t.line, t.col)
            return ex.DoubleLiteral(value=fv)
        if t.type == TokType.DECIMAL:
            self.next()
            return ex.DecimalLiteral(text=t.text)
        if t.type == TokType.VARIABLE:
            self.next()
            return ex.StringLiteral(value="${" + t.text + "}")
        # parenthesized / lambda
        if self.at_op("("):
            if self._is_lambda_ahead():
                self.next()
                params = [self.identifier()]
                while self.accept_op(","):
                    params.append(self.identifier())
                self.expect_op(")")
                self.expect_op("=>")
                return ex.LambdaExpression(params=tuple(params), body=self.parse_expression())
            self.next()
            inner = self.parse_expression()
            self.expect_op(")")
            return inner
        if t.type == TokType.IDENT:
            kw = t.text
            if kw == "NULL":
                self.next()
                return ex.NullLiteral()
            if kw in ("TRUE", "FALSE"):
                self.next()
                return ex.BooleanLiteral(value=kw == "TRUE")
            if kw == "CAST":
                self.next()
                self.expect_op("(")
                operand = self.parse_expression()
                self.expect_kw("AS")
                target = self.parse_type()
                self.expect_op(")")
                return ex.Cast(operand=operand, target=target)
            if kw == "CASE":
                return self._parse_case()
            if kw == "ARRAY" and self.peek(1).type == TokType.OP and self.peek(1).text == "[":
                self.next()
                self.next()
                items = []
                if not self.at_op("]"):
                    items = self._expression_list()
                self.expect_op("]")
                return ex.CreateArray(items=tuple(items))
            if kw == "MAP" and self.peek(1).type == TokType.OP and self.peek(1).text == "(":
                self.next()
                self.next()
                entries: List[Tuple[ex.Expression, ex.Expression]] = []
                if not self.at_op(")"):
                    while True:
                        k = self.parse_expression()
                        self.expect_op(":=")
                        v = self.parse_expression()
                        entries.append((k, v))
                        if not self.accept_op(","):
                            break
                self.expect_op(")")
                return ex.CreateMap(entries=tuple(entries))
            if kw == "STRUCT" and self.peek(1).type == TokType.OP and self.peek(1).text == "(":
                self.next()
                self.next()
                fields: List[Tuple[str, ex.Expression]] = []
                if not self.at_op(")"):
                    while True:
                        fname = self.identifier()
                        self.expect_op(":=")
                        fields.append((fname, self.parse_expression()))
                        if not self.accept_op(","):
                            break
                self.expect_op(")")
                return ex.CreateStruct(fields=tuple(fields))
            if kw in ("TIME", "DATE", "TIMESTAMP") and self.peek(1).type == TokType.STRING:
                self.next()
                text = self.next().text
                return {
                    "TIME": ex.TimeLiteral,
                    "DATE": ex.DateLiteral,
                    "TIMESTAMP": ex.TimestampLiteral,
                }[kw](text=text)
            if kw == "X" and self.peek(1).type == TokType.STRING:
                self.next()
                hex_tok = self.next()
                try:
                    return ex.BytesLiteral(value=bytes.fromhex(hex_tok.text))
                except ValueError:
                    self.err("invalid hex in bytes literal", hex_tok)
        # identifier-led: lambda var, function call, column ref
        if t.type in (TokType.IDENT, TokType.QIDENT):
            if self.peek(1).type == TokType.OP and self.peek(1).text == "=>":
                name = self.identifier()
                self.next()  # =>
                return ex.LambdaExpression(params=(name,), body=self.parse_expression())
            name = self.identifier()
            if self.at_op("("):
                self.next()
                distinct = bool(self.accept_kw("DISTINCT"))
                args: List[ex.Expression] = []
                if self.accept_op("*"):
                    pass  # COUNT(*) -> zero-arg
                elif not self.at_op(")"):
                    args = self._expression_list()
                self.expect_op(")")
                return ex.FunctionCall(name=name.upper(), args=tuple(args), distinct=distinct)
            if self.at_op(".") and self.peek(1).type in (TokType.IDENT, TokType.QIDENT):
                self.next()
                col = self.identifier()
                return ex.ColumnRef(name=col, source=name)
            return ex.ColumnRef(name=name)
        self.err("expected expression")

    def _parse_case(self) -> ex.Expression:
        self.expect_kw("CASE")
        operand = None
        if not self.at_kw("WHEN"):
            operand = self.parse_expression()
        whens: List[ex.WhenClause] = []
        while self.accept_kw("WHEN"):
            cond = self.parse_expression()
            self.expect_kw("THEN")
            result = self.parse_expression()
            whens.append(ex.WhenClause(condition=cond, result=result))
        default = None
        if self.accept_kw("ELSE"):
            default = self.parse_expression()
        self.expect_kw("END")
        if operand is not None:
            return ex.SimpleCase(operand=operand, when_clauses=tuple(whens), default=default)
        return ex.SearchedCase(when_clauses=tuple(whens), default=default)


# -------------------------------------------------------------- public API


def substitute_variables(sql: str, variables: Dict[str, str]) -> str:
    """Session-variable substitution (VariableSubstitutor.java:35).  Variable
    names are case-insensitive (DEFINE upper-cases unquoted names).  Performed
    textually before lexing; leftovers lex as VARIABLE tokens."""
    import re

    lowered = {k.lower(): v for k, v in variables.items()}

    def repl(m: "re.Match[str]") -> str:
        return lowered.get(m.group(1).lower(), m.group(0))

    return re.sub(r"\$\{(\w+)\}", repl, sql)


def parse_statements(
    sql: str,
    variables: Optional[Dict[str, str]] = None,
    type_registry: Optional[Dict[str, SqlType]] = None,
) -> List[ast.PreparedStatement]:
    return Parser(sql, variables, type_registry).parse_statements()


def parse_statement(
    sql: str,
    variables: Optional[Dict[str, str]] = None,
    type_registry: Optional[Dict[str, SqlType]] = None,
) -> ast.Statement:
    stmts = parse_statements(sql, variables, type_registry)
    if len(stmts) != 1:
        raise ParsingException(f"expected exactly one statement, got {len(stmts)}")
    return stmts[0].statement


def parse_expression(sql: str) -> ex.Expression:
    p = Parser(sql)
    e = p.parse_expression()
    if p.peek().type != TokType.EOF:
        p.err("trailing input after expression")
    return e
