"""Statement AST.

Analog of the 77 node classes in ksqldb-parser/.../parser/tree/ — the subset
that carries real semantics, organized the same way: statements, relations,
select items, window expressions.  Reuses the expression-node registry for
JSON round-trip (EXPLAIN plans embed ASTs).
"""

import enum
from typing import Any, Dict, Optional, Tuple

from ksql_tpu.common.types import SqlType
from ksql_tpu.execution.expressions import (
    Expression,
    node,
    register_enum,
)


class Statement:
    """Marker base for statements."""


class Relation:
    """Marker base for FROM-clause relations."""


# ------------------------------------------------------------- select items


@node
class AllColumns:
    source: Optional[str] = None  # `s.*`


@node
class SingleColumn:
    expression: Expression
    alias: Optional[str] = None


@node
class Select:
    items: Tuple[Any, ...]  # AllColumns | SingleColumn


# ---------------------------------------------------------------- relations


@node
class Table(Relation):
    name: str


@node
class AliasedRelation(Relation):
    relation: Relation
    alias: str


@register_enum
class JoinType(enum.Enum):
    INNER = "INNER"
    LEFT = "LEFT"
    RIGHT = "RIGHT"
    OUTER = "OUTER"


@node
class WithinExpression:
    """Stream-stream join window: WITHIN n UNIT [GRACE PERIOD n UNIT] or
    WITHIN (before, after)."""

    before_ms: int
    after_ms: int
    grace_ms: Optional[int] = None


@node
class JoinOn:
    expression: Expression


@node
class Join(Relation):
    join_type: JoinType
    left: Relation
    right: Relation
    criteria: Optional[JoinOn] = None
    within: Optional[WithinExpression] = None


# ------------------------------------------------------------------ windows


@register_enum
class WindowType(enum.Enum):
    TUMBLING = "TUMBLING"
    HOPPING = "HOPPING"
    SESSION = "SESSION"


@node
class WindowExpression:
    """WINDOW TUMBLING (SIZE 1 HOUR[, RETENTION ..][, GRACE PERIOD ..]) etc.
    All durations normalized to ms at parse time
    (reference: ksqldb-execution/.../windows/)."""

    window_type: WindowType
    size_ms: Optional[int] = None  # tumbling/hopping
    advance_ms: Optional[int] = None  # hopping
    gap_ms: Optional[int] = None  # session
    retention_ms: Optional[int] = None
    grace_ms: Optional[int] = None


# -------------------------------------------------------------------- query


@register_enum
class RefinementType(enum.Enum):
    CHANGES = "CHANGES"
    FINAL = "FINAL"


@node
class Refinement:
    type: RefinementType


@node
class Query(Statement):
    select: Select
    from_: Relation
    window: Optional[WindowExpression] = None
    where: Optional[Expression] = None
    group_by: Tuple[Expression, ...] = ()
    partition_by: Tuple[Expression, ...] = ()
    having: Optional[Expression] = None
    refinement: Optional[Refinement] = None
    limit: Optional[int] = None


# ---------------------------------------------------------------------- DDL


@register_enum
class ColumnConstraint(enum.Enum):
    NONE = "NONE"
    KEY = "KEY"
    PRIMARY_KEY = "PRIMARY_KEY"
    HEADERS = "HEADERS"


@node
class TableElement:
    name: str
    type: SqlType
    constraint: ColumnConstraint = ColumnConstraint.NONE
    header_key: Optional[str] = None  # HEADER('key')


@node
class CreateStream(Statement):
    name: str
    elements: Tuple[TableElement, ...]
    properties: Dict[str, Any]
    if_not_exists: bool = False
    or_replace: bool = False
    is_source: bool = False


@node
class CreateTable(Statement):
    name: str
    elements: Tuple[TableElement, ...]
    properties: Dict[str, Any]
    if_not_exists: bool = False
    or_replace: bool = False
    is_source: bool = False


@node
class CreateStreamAsSelect(Statement):
    name: str
    query: Query
    properties: Dict[str, Any]
    if_not_exists: bool = False
    or_replace: bool = False


@node
class CreateTableAsSelect(Statement):
    name: str
    query: Query
    properties: Dict[str, Any]
    if_not_exists: bool = False
    or_replace: bool = False


@node
class InsertInto(Statement):
    target: str
    query: Query


@node
class InsertValues(Statement):
    target: str
    columns: Tuple[str, ...]
    values: Tuple[Expression, ...]


@node
class DropSource(Statement):
    name: str
    is_table: bool
    if_exists: bool = False
    delete_topic: bool = False


@node
class AlterSource(Statement):
    """ALTER STREAM|TABLE <name> ADD COLUMN <col> <type>, ..."""

    name: str
    is_table: bool
    new_columns: Tuple[TableElement, ...]


@node
class RegisterType(Statement):
    name: str
    type: SqlType
    if_not_exists: bool = False


@node
class DropType(Statement):
    name: str
    if_exists: bool = False


# -------------------------------------------------------------------- admin


@node
class ListStreams(Statement):
    extended: bool = False


@node
class ListTables(Statement):
    extended: bool = False


@node
class ListTopics(Statement):
    show_all: bool = False
    extended: bool = False


@node
class ListQueries(Statement):
    extended: bool = False


@node
class ListProperties(Statement):
    pass


@node
class ListFunctions(Statement):
    pass


@node
class ListTypes(Statement):
    pass


@node
class ListVariables(Statement):
    pass


@node
class ShowColumns(Statement):
    """DESCRIBE <source> [EXTENDED]"""

    source: str
    extended: bool = False


@node
class DescribeFunction(Statement):
    name: str


@node
class DescribeStreams(Statement):
    extended: bool = False


@node
class DescribeTables(Statement):
    extended: bool = False


@node
class Explain(Statement):
    query_id: Optional[str] = None
    statement: Optional[Statement] = None
    # EXPLAIN ANALYZE <query_id>: per-stage p50/p99 from the flight recorder
    analyze: bool = False


@node
class TerminateQuery(Statement):
    query_id: Optional[str] = None  # None = TERMINATE ALL


@node
class PauseQuery(Statement):
    query_id: Optional[str] = None


@node
class ResumeQuery(Statement):
    query_id: Optional[str] = None


@node
class SetProperty(Statement):
    name: str
    value: str


@node
class UnsetProperty(Statement):
    name: str


@node
class AlterSystemProperty(Statement):
    name: str
    value: str


@node
class DefineVariable(Statement):
    name: str
    value: str


@node
class UndefineVariable(Statement):
    name: str


@node
class CreateConnector(Statement):
    name: str
    properties: Dict[str, Any]
    connector_type: str = "SOURCE"  # SOURCE | SINK
    if_not_exists: bool = False


@node
class DropConnector(Statement):
    name: str
    if_exists: bool = False


@node
class ListConnectors(Statement):
    scope: str = "ALL"  # SOURCE | SINK | ALL


@node
class DescribeConnector(Statement):
    name: str


# ------------------------------------------------------- testing statements


@node
class AssertValues(Statement):
    """ASSERT VALUES <source> (cols) VALUES (exprs) — testing tool.  The
    `ASSERT NULL VALUES` / `ASSERT TOMBSTONE` forms parse to AssertTombstone."""

    source: str
    columns: Tuple[str, ...]
    values: Tuple[Expression, ...]


@node
class AssertStream(Statement):
    statement: CreateStream


@node
class AssertTable(Statement):
    statement: CreateTable


@node
class AssertTombstone(Statement):
    source: str
    columns: Tuple[str, ...]
    values: Tuple[Expression, ...]


@node
class RunScript(Statement):
    path: str


@node
class PrintTopic(Statement):
    topic: str
    from_beginning: bool = False
    interval: Optional[int] = None
    limit: Optional[int] = None


@node
class PreparedStatement:
    """Statement + original text (KsqlParser.PreparedStatement analog)."""

    text: str
    statement: Statement
