"""SQL lexer.

Token-level behavior mirrors the reference grammar
(ksqldb-parser/src/main/antlr4/.../SqlBase.g4:560-673): case-insensitive
keywords, unquoted identifiers fold to upper case, backquoted identifiers
preserve case, `'...'` strings with `''` escape, `--` and `/* */` comments,
`${var}` session-variable references.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from ksql_tpu.common.errors import ParsingException


class TokType:
    IDENT = "IDENT"  # unquoted, already upper-cased
    QIDENT = "QIDENT"  # backquoted, case preserved
    STRING = "STRING"
    INTEGER = "INTEGER"
    FLOAT = "FLOAT"  # has exponent -> DOUBLE
    DECIMAL = "DECIMAL"  # has dot, no exponent -> DECIMAL literal
    OP = "OP"
    VARIABLE = "VARIABLE"  # ${name}
    EOF = "EOF"


@dataclasses.dataclass(frozen=True)
class Token:
    type: str
    text: str
    line: int
    col: int
    raw: str = ""  # original (pre-case-fold) text for IDENT tokens

    def __repr__(self) -> str:
        return f"{self.type}({self.text!r})"


_TWO_CHAR_OPS = ("<>", "!=", "<=", ">=", "->", "=>", "::", ":=")
_ONE_CHAR_OPS = "+-*/%<>=(),.;[]{}:"


def tokenize(sql: str) -> List[Token]:
    tokens: List[Token] = []
    i, n = 0, len(sql)
    line, line_start = 1, 0

    def pos():
        return line, i - line_start

    def err(msg: str):
        l, c = pos()
        raise ParsingException(msg, l, c)

    while i < n:
        ch = sql[i]
        if ch == "\n":
            line += 1
            line_start = i + 1
            i += 1
            continue
        if ch in " \t\r":
            i += 1
            continue
        # comments
        if sql.startswith("--", i):
            j = sql.find("\n", i)
            i = n if j < 0 else j
            continue
        if sql.startswith("/*", i):
            j = sql.find("*/", i + 2)
            if j < 0:
                err("unterminated block comment")
            line += sql.count("\n", i, j)
            if "\n" in sql[i:j]:
                line_start = i + sql[i:j].rfind("\n") + 1
            i = j + 2
            continue
        l, c = pos()
        # string literal
        if ch == "'":
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    err("unterminated string literal")
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        buf.append("'")
                        j += 2
                        continue
                    break
                if sql[j] == "\n":
                    line += 1
                    line_start = j + 1
                buf.append(sql[j])
                j += 1
            tokens.append(Token(TokType.STRING, "".join(buf), l, c))
            i = j + 1
            continue
        # quoted identifier: backquoted (`` escape) or double-quoted ("" escape)
        if ch in ("`", '"'):
            q = ch
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    err("unterminated quoted identifier")
                if sql[j] == q:
                    if j + 1 < n and sql[j + 1] == q:
                        buf.append(q)
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            tokens.append(Token(TokType.QIDENT, "".join(buf), l, c))
            i = j + 1
            continue
        # session variable ${name}
        if sql.startswith("${", i):
            j = sql.find("}", i + 2)
            if j < 0:
                err("unterminated variable reference")
            tokens.append(Token(TokType.VARIABLE, sql[i + 2 : j], l, c))
            i = j + 1
            continue
        # number
        if ch.isdigit() or (ch == "." and i + 1 < n and sql[i + 1].isdigit()):
            j = i
            has_dot = False
            has_exp = False
            while j < n:
                cj = sql[j]
                if cj.isdigit():
                    j += 1
                elif cj == "." and not has_dot and not has_exp:
                    # don't swallow `1.e` confusion; simple dot handling
                    has_dot = True
                    j += 1
                elif cj in "eE" and not has_exp and j + 1 < n and (
                    sql[j + 1].isdigit() or (sql[j + 1] in "+-" and j + 2 < n and sql[j + 2].isdigit())
                ):
                    has_exp = True
                    j += 2 if sql[j + 1] in "+-" else 1
                else:
                    break
            # digit-leading identifier (SqlBase.g4 DIGIT_IDENTIFIER, e.g. `1R`)
            if (
                not has_dot
                and not has_exp
                and j < n
                and (sql[j].isalpha() or sql[j] == "_")
            ):
                while j < n and (sql[j].isalnum() or sql[j] == "_"):
                    j += 1
                tokens.append(Token(TokType.IDENT, sql[i:j].upper(), l, c, raw=sql[i:j]))
                i = j
                continue
            text = sql[i:j]
            if has_exp:
                t = TokType.FLOAT
            elif has_dot:
                t = TokType.DECIMAL
            else:
                t = TokType.INTEGER
            tokens.append(Token(t, text, l, c))
            i = j
            continue
        # hex bytes literal X'...' handled in parser via IDENT X + STRING
        # identifier / keyword
        if ch.isalpha() or ch == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            tokens.append(Token(TokType.IDENT, sql[i:j].upper(), l, c, raw=sql[i:j]))
            i = j
            continue
        # operators
        two = sql[i : i + 2]
        if two in _TWO_CHAR_OPS:
            tokens.append(Token(TokType.OP, two, l, c))
            i += 2
            continue
        if ch in _ONE_CHAR_OPS:
            tokens.append(Token(TokType.OP, ch, l, c))
            i += 1
            continue
        err(f"unexpected character {ch!r}")
    tokens.append(Token(TokType.EOF, "", line, 0))
    return tokens
