"""Data generator.

Analog of ksqldb-examples datagen (DataGen.java:47, DataGenProducer.java):
produces randomly generated rows for load testing and quickstarts.  The
reference drives Avro-random-generator schemas; here the quickstart schemas
(users, pageviews, orders — the reference's bundled quickstarts) are built
in, plus a generic generator over any LogicalSchema.
"""

from __future__ import annotations

import random
import string
import time
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from ksql_tpu.common.schema import LogicalSchema
from ksql_tpu.common.types import SqlBaseType, SqlType


def _rand_string(rng: random.Random, n: int = 8) -> str:
    return "".join(rng.choice(string.ascii_lowercase) for _ in range(n))


def random_value(rng: random.Random, t: SqlType) -> Any:
    b = t.base
    if b == SqlBaseType.BOOLEAN:
        return rng.random() < 0.5
    if b == SqlBaseType.INTEGER:
        return rng.randint(0, 1000)
    if b == SqlBaseType.BIGINT:
        return rng.randint(0, 10**9)
    if b in (SqlBaseType.DOUBLE, SqlBaseType.DECIMAL):
        return round(rng.random() * 1000, 2)
    if b == SqlBaseType.STRING:
        return _rand_string(rng)
    if b == SqlBaseType.BYTES:
        return bytes(rng.getrandbits(8) for _ in range(8))
    if b == SqlBaseType.TIMESTAMP:
        return int(time.time() * 1000) - rng.randint(0, 86_400_000)
    if b == SqlBaseType.DATE:
        return rng.randint(18000, 20000)
    if b == SqlBaseType.TIME:
        return rng.randint(0, 86_399_999)
    if b == SqlBaseType.ARRAY:
        return [random_value(rng, t.element) for _ in range(rng.randint(0, 4))]
    if b == SqlBaseType.MAP:
        return {_rand_string(rng, 4): random_value(rng, t.element)
                for _ in range(rng.randint(0, 3))}
    if b == SqlBaseType.STRUCT:
        return {n: random_value(rng, ft) for n, ft in (t.fields or ())}
    return None


# ----------------------------------------------------- quickstart generators

_USERS = ["alice", "bob", "carol", "dan", "erin", "frank", "grace", "heidi"]
_REGIONS = [f"Region_{i}" for i in range(1, 10)]
_GENDERS = ["MALE", "FEMALE", "OTHER"]
_PAGES = [f"Page_{i}" for i in range(1, 100)]
_STATUSES = ["SHIPPED", "PENDING", "DELIVERED", "CANCELLED"]


def _users_row(rng: random.Random, i: int) -> Tuple[Any, Dict[str, Any]]:
    uid = rng.choice(_USERS)
    return uid, {
        "REGISTERTIME": int(time.time() * 1000) - rng.randint(0, 10**8),
        "USERID": uid,
        "REGIONID": rng.choice(_REGIONS),
        "GENDER": rng.choice(_GENDERS),
    }


def _pageviews_row(rng: random.Random, i: int) -> Tuple[Any, Dict[str, Any]]:
    return str(i), {
        "VIEWTIME": int(time.time() * 1000),
        "USERID": rng.choice(_USERS),
        "PAGEID": rng.choice(_PAGES),
    }


def _orders_row(rng: random.Random, i: int) -> Tuple[Any, Dict[str, Any]]:
    return i, {
        "ORDERTIME": int(time.time() * 1000),
        "ORDERID": i,
        "ITEMID": f"Item_{rng.randint(1, 200)}",
        "ORDERUNITS": round(rng.random() * 10, 3),
        "ADDRESS": {
            "CITY": _rand_string(rng, 6).title(),
            "STATE": _rand_string(rng, 2).upper(),
            "ZIPCODE": rng.randint(10000, 99999),
        },
    }


QUICKSTARTS: Dict[str, Callable[[random.Random, int], Tuple[Any, Dict[str, Any]]]] = {
    "users": _users_row,
    "pageviews": _pageviews_row,
    "orders": _orders_row,
}

QUICKSTART_DDL = {
    "users": (
        "CREATE STREAM users (USERID STRING KEY, REGISTERTIME BIGINT, "
        "REGIONID STRING, GENDER STRING) WITH (kafka_topic='users', "
        "value_format='JSON');"
    ),
    "pageviews": (
        "CREATE STREAM pageviews (PVID STRING KEY, VIEWTIME BIGINT, "
        "USERID STRING, PAGEID STRING) WITH (kafka_topic='pageviews', "
        "value_format='JSON');"
    ),
    "orders": (
        "CREATE STREAM orders (ORDERKEY BIGINT KEY, ORDERTIME BIGINT, ORDERID BIGINT, "
        "ITEMID STRING, ORDERUNITS DOUBLE, ADDRESS STRUCT<CITY STRING, "
        "STATE STRING, ZIPCODE BIGINT>) WITH (kafka_topic='orders', "
        "value_format='JSON');"
    ),
}


class DataGen:
    """Produces generated records to a broker topic (DataGenProducer)."""

    def __init__(self, broker, quickstart: Optional[str] = None,
                 schema: Optional[LogicalSchema] = None,
                 topic: Optional[str] = None, seed: Optional[int] = None,
                 rate: Optional[float] = None):
        if quickstart is None and schema is None:
            raise ValueError("need quickstart or schema")
        self.broker = broker
        self.quickstart = quickstart
        self.schema = schema
        self.topic_name = topic or quickstart
        self.rng = random.Random(seed)
        self.rate = rate  # msgs/sec, None = unthrottled

    def rows(self, n: int) -> Iterator[Tuple[Any, Dict[str, Any]]]:
        gen = QUICKSTARTS.get(self.quickstart) if self.quickstart else None
        for i in range(n):
            if gen is not None:
                yield gen(self.rng, i)
            else:
                key = tuple(
                    random_value(self.rng, c.type) for c in self.schema.key_columns
                )
                row = {c.name: random_value(self.rng, c.type)
                       for c in self.schema.value_columns}
                yield (key[0] if len(key) == 1 else (key or None)), row

    def produce(self, n: int, value_format: str = "JSON") -> int:
        """Generate and produce n records; returns count produced."""
        import json as _json

        from ksql_tpu.runtime.topics import Record

        topic = self.broker.create_topic(self.topic_name)
        count = 0
        for key, row in self.rows(n):
            ts = int(time.time() * 1000)
            topic.produce(Record(
                key=key, value=_json.dumps(row), timestamp=ts, partition=-1,
            ))
            count += 1
            if self.rate:
                time.sleep(1.0 / self.rate)
        return count


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    from ksql_tpu.runtime.topics import Broker

    p = argparse.ArgumentParser(prog="ksql-tpu-datagen")
    p.add_argument("quickstart", choices=sorted(QUICKSTARTS))
    p.add_argument("--iterations", type=int, default=100)
    p.add_argument("--seed", type=int, default=None)
    args = p.parse_args(argv)
    broker = Broker()
    gen = DataGen(broker, quickstart=args.quickstart, seed=args.seed)
    n = gen.produce(args.iterations)
    for r in broker.topic(gen.topic_name).all_records()[:5]:
        print(r.key, r.value)
    print(f"produced {n} records to {gen.topic_name}")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
