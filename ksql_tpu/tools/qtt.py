"""QTT (Query Translation Test) harness.

Runs the reference's golden-file format verbatim
(ksqldb-functional-tests/src/test/resources/query-validation-tests/*.json,
format shown at average.json:12-33): statements + input records + expected
output records, executed on a fresh engine with one record piped at a time
(TopologyTestDriver semantics — TestExecutor.java:99).
"""

from __future__ import annotations

import base64
import dataclasses
import json
import math
from typing import Any, Dict, List, Optional, Tuple

from ksql_tpu.engine.engine import KsqlEngine
from ksql_tpu.runtime.topics import Record


@dataclasses.dataclass
class CaseResult:
    name: str
    file: str
    status: str  # PASS | FAIL | ERROR | SKIP | XFAIL_MATCHED | XFAIL_LOOSE
    detail: str = ""


def _norm_err(s: str) -> str:
    import re as _re

    return _re.sub(r"\s+", " ", s).strip().casefold()


def _err_matches(want: str, got: str) -> bool:
    """Expected-message-contained-in-actual after whitespace/case
    normalization — the reference's expectedException uses hamcrest
    containsString on the message (TestExecutor.java:99 plumbing).  The
    check is deliberately one-directional: accepting actual-in-expected too
    let any terse engine error (e.g. a bare "unsupported") "match" a long
    expectation and inflated the XFAIL_MATCHED parity stats."""
    if not want:
        return True  # type-only expectation: nothing comparable to a Java class
    return _norm_err(want) in _norm_err(got)


def _xfail_result(name, file, case, msg, prefix=""):
    """An expectedException case that did raise: MATCHED when the message
    lines up with the case's expectation, LOOSE otherwise (an
    unimplemented-feature error is indistinguishable from the intended
    validation error unless the text is compared)."""
    want = (case.get("expectedException") or {}).get("message", "")
    status = "XFAIL_MATCHED" if _err_matches(want, msg) else "XFAIL_LOOSE"
    return CaseResult(name, file, status, (prefix + msg)[:160])


def _is_decimal_typed(typ) -> bool:
    from ksql_tpu.common.types import SqlBaseType

    return typ is not None and getattr(typ, "base", None) == SqlBaseType.DECIMAL


def _field_type(typ, name: str):
    """Child type for a struct field / array element / map value, if known."""
    from ksql_tpu.common.types import SqlBaseType

    if typ is None:
        return None
    if typ.base == SqlBaseType.STRUCT and typ.fields:
        for fn, ft in typ.fields:
            if fn.upper() == name.upper():
                return ft
    return None


def _values_equal(expected: Any, actual: Any, typ=None) -> bool:
    import decimal as _dec

    if isinstance(actual, _dec.Decimal):
        if isinstance(expected, str):
            try:
                return _dec.Decimal(expected) == actual
            except _dec.InvalidOperation:
                return False
        actual = float(actual)
    if (
        isinstance(expected, (int, float))
        and not isinstance(expected, bool)
        and isinstance(actual, str)
        and _is_decimal_typed(typ)
    ):
        # decimal rendered as fixed-point text vs a numeric expectation
        try:
            a = _dec.Decimal(actual)
        except _dec.InvalidOperation:
            return False
        if isinstance(expected, int):
            return a == expected
        return math.isclose(float(a), expected, rel_tol=1e-9, abs_tol=1e-9)
    if isinstance(expected, _dec.Decimal):
        if isinstance(actual, str):
            try:
                return _dec.Decimal(actual) == expected
            except _dec.InvalidOperation:
                return False
        expected = float(expected)
    if expected is None or actual is None:
        return expected is None and actual is None
    if isinstance(expected, bool) or isinstance(actual, bool):
        return expected == actual
    if isinstance(expected, (int, float)) and isinstance(actual, (int, float)):
        if isinstance(expected, float) or isinstance(actual, float):
            return math.isclose(float(expected), float(actual), rel_tol=1e-9, abs_tol=1e-9)
        return expected == actual
    if isinstance(expected, str) and isinstance(actual, str) and expected != actual:
        # decimal text may differ in padding/scale across formats; the
        # reference comparison is typed (BigDecimal compareTo).  Applies when
        # the column is known DECIMAL (any fixed-point text), or when the
        # type is unknown and both sides are fixed-point text WITH a
        # fraction — so genuine STRING-column differences still fail
        import decimal
        import re as _re

        if _is_decimal_typed(typ):
            if _re.fullmatch(r"-?\d+(\.\d+)?", expected) and _re.fullmatch(
                r"-?\d+(\.\d+)?", actual
            ):
                return decimal.Decimal(expected) == decimal.Decimal(actual)
            return False
        if typ is None and _re.fullmatch(r"-?\d+\.\d+", expected) and _re.fullmatch(
            r"-?\d+\.\d+", actual
        ):
            return decimal.Decimal(expected) == decimal.Decimal(actual)
        return False
    if isinstance(expected, dict) and isinstance(actual, dict):
        e = {str(k).upper(): v for k, v in expected.items()}
        a = {str(k).upper(): v for k, v in actual.items()}
        # a field present on one side only compares as null (the reference
        # comparator treats absent struct fields as null values)
        from ksql_tpu.common.types import SqlBaseType

        if typ is not None and typ.base == SqlBaseType.MAP:
            # MAP keys are case-sensitive data (unlike struct field names)
            vt = typ.element
            return all(
                _values_equal(expected.get(k), actual.get(k), vt)
                for k in set(expected) | set(actual)
            )
        return all(
            _values_equal(e.get(k), a.get(k), _field_type(typ, k))
            for k in set(e) | set(a)
        )
    if isinstance(expected, list) and isinstance(actual, list):
        et = typ.element if typ is not None and typ.element is not None else None
        return len(expected) == len(actual) and all(
            _values_equal(x, y, et) for x, y in zip(expected, actual)
        )
    if isinstance(expected, str) and isinstance(actual, bool):
        return expected == ("true" if actual else "false")
    # decimals cross formats as either padded strings or numbers; the
    # reference comparison is typed (BigDecimal equality), so fall back to
    # exact numeric comparison for str-vs-number pairs
    if (
        isinstance(expected, str)
        and isinstance(actual, (int, float))
        or isinstance(actual, str)
        and isinstance(expected, (int, float))
    ):
        s, n = (expected, actual) if isinstance(expected, str) else (actual, expected)
        if s == str(n):
            return True
        import decimal
        import re as _re

        # plain fixed-point text only (no exponent); BigDecimal-style compare
        if not _re.fullmatch(r"-?\d+(\.\d+)?", s):
            return False
        try:
            return decimal.Decimal(s) == decimal.Decimal(repr(n))
        except decimal.InvalidOperation:
            return False
    if isinstance(expected, str) and isinstance(actual, bytes):
        if expected == base64.b64encode(actual).decode("ascii"):
            return True
        try:
            return expected == actual.decode("utf-8")
        except UnicodeDecodeError:
            return False
    return expected == actual


def _parse_payload(payload: Any) -> Any:
    if isinstance(payload, str):
        try:
            return json.loads(payload)
        except (ValueError, TypeError):
            return payload
    return payload


def run_case(case: Dict[str, Any], file: str = "") -> CaseResult:
    name = case.get("name", "unnamed")
    expects_error = "expectedException" in case
    # QTT_BACKEND=device runs device-eligible cases on the XLA backend
    # (batch size 1 for per-record changelog parity); default is the row
    # oracle — compile latency across 2k+ cases dominates otherwise
    import os

    from ksql_tpu.common.config import KsqlConfig, RUNTIME_BACKEND

    backend = os.environ.get("QTT_BACKEND", "oracle")
    if backend != "oracle":
        # pin JAX to CPU in-process: a 2k-case parity sweep must not seize
        # the (shared) TPU chip, and CPU keeps per-case compiles cheap
        import jax

        if not jax.config.jax_platforms:
            jax.config.update("jax_platforms", "cpu")
    from ksql_tpu.common.config import (
        EMIT_CHANGES_PER_RECORD,
        PROCESSING_LOG_TOPIC_AUTO_CREATE,
    )

    engine = KsqlEngine(
        KsqlConfig(
            {
                RUNTIME_BACKEND: backend,
                # the reference QTT harness runs without the processing-log
                # stream; SHOW STREAMS expectations assume it is absent
                PROCESSING_LOG_TOPIC_AUTO_CREATE: False,
                # golden files expect per-record changelog cadence
                # (TopologyTestDriver pipes one record at a time, cache off)
                EMIT_CHANGES_PER_RECORD: True,
            }
        )
    )
    engine.session_properties.update(case.get("properties", {}))
    try:
        # register case topics: partitions + SR schemas (TestCase 'topics')
        # reference QTT harness creates every topic with 4 partitions by
        # default (testing-tool model/Topic.java:30 DEFAULT_PARTITIONS = 4)
        for t in case.get("topics", ()):
            if isinstance(t, str):
                engine.broker.create_topic(t, 4)
                continue
            engine.broker.create_topic(
                t["name"], int(t.get("partitions", 4) or 4)
            )
            if t.get("keySchema") is not None:
                args = (
                    f"{t['name']}-key",
                    str(t.get("keyFormat", "AVRO")),
                    t["keySchema"],
                    tuple(r.get("schema") for r in t.get("keySchemaReferences", ())),
                )
                if t.get("keySchemaId") is not None:
                    engine.schema_registry.register(*args, schema_id=int(t["keySchemaId"]))
                else:
                    engine.schema_registry.add_pending(*args)
            if t.get("valueSchema") is not None:
                args = (
                    f"{t['name']}-value",
                    str(t.get("valueFormat", "AVRO")),
                    t["valueSchema"],
                    tuple(r.get("schema") for r in t.get("valueSchemaReferences", ())),
                )
                if t.get("valueSchemaId") is not None:
                    engine.schema_registry.register(*args, schema_id=int(t["valueSchemaId"]))
                else:
                    engine.schema_registry.add_pending(*args)
        # register input topics ahead of DDL (reference creates them eagerly,
        # 4 partitions by default)
        for rec in case.get("inputs", ()):  # ensure topic exists
            engine.broker.create_topic(rec["topic"], 4)
        for stmt in case.get("statements", ()):
            for prepared in engine.parse(stmt):
                engine.execute_statement(prepared)
    except Exception as e:
        msg = str(e)
        if expects_error:
            return _xfail_result(name, file, case, msg)
        if "unknown function" in msg:
            # a function the build genuinely lacks (none today: the ext/
            # shim registers every harness function)
            return CaseResult(name, file, "SKIP", msg[:100])
        if "schema inference" in msg:
            return CaseResult(name, file, "SKIP", msg[:100])
        return CaseResult(name, file, "ERROR", f"{type(e).__name__}: {msg[:200]}")
    if expects_error:
        # the error may legitimately surface at runtime (serde/eval errors go
        # to the processing log); feed inputs and check it
        try:
            for rec in case.get("inputs", ()):
                topic = engine.broker.create_topic(rec["topic"])
                topic.produce(Record(
                    key=rec.get("key"), value=rec.get("value"),
                    timestamp=int(rec.get("timestamp", 0)),
                    # TopologyTestDriver pipes all inputs through partition 0
                    partition=0,
                ))
                engine.run_until_quiescent()
        except Exception as e:
            return _xfail_result(name, file, case, str(e))
        if engine.processing_log:
            return _xfail_result(
                name, file, case,
                engine.processing_log[0][1], prefix="runtime error: ",
            )
        return CaseResult(name, file, "FAIL", "expected exception not raised")

    try:
        sink_offsets: Dict[str, int] = {}
        # record end offsets of sink topics before input (in case of pre-existing)
        for rec in case.get("inputs", ()):
            topic = engine.broker.create_topic(rec["topic"])
            r = Record(
                key=rec.get("key"),
                value=rec.get("value"),
                timestamp=int(rec.get("timestamp", 0)),
                partition=0,  # TopologyTestDriver: single input partition
                headers=tuple(
                    (h.get("KEY"),
                     base64.b64decode(h["VALUE"]) if h.get("VALUE") is not None else None)
                    for h in rec.get("headers", ())
                ),
                window=(
                    (rec["window"]["start"], rec["window"]["end"])
                    if "window" in rec
                    else None
                ),
            )
            topic.produce(r)
            engine.run_until_quiescent()
        # NOTE: no end-of-input time flush — the reference TopologyTestDriver
        # only advances stream time with actual records, so windows that never
        # close within the input produce no output.

        # collect actual outputs per topic
        expected = case.get("outputs", [])
        actual_by_topic: Dict[str, List[Record]] = {}
        for out in expected:
            tn = out["topic"]
            if tn not in actual_by_topic and engine.broker.has_topic(tn):
                # global produce order across partitions, as the reference's
                # TopologyTestDriver observes outputs
                actual_by_topic[tn] = engine.broker.topic(tn).all_records()
        # sink row types (topic -> STRUCT of value columns) let the comparator
        # apply decimal semantics only to DECIMAL-typed columns
        from ksql_tpu.common.types import SqlType

        topic_types: Dict[str, Any] = {}
        for src in engine.metastore.all_sources():
            if src.topic in actual_by_topic and src.topic not in topic_types:
                topic_types[src.topic] = SqlType.struct(
                    [(c.name, c.type) for c in src.schema.value_columns]
                )
        positions: Dict[str, int] = {t: 0 for t in actual_by_topic}
        for i, out in enumerate(expected):
            tn = out["topic"]
            recs = actual_by_topic.get(tn, [])
            pos = positions.get(tn, 0)
            if pos >= len(recs):
                return CaseResult(
                    name, file, "FAIL",
                    f"missing output #{i} on {tn}: expected {json.dumps(out)[:120]}"
                )
            rec = recs[pos]
            positions[tn] = pos + 1
            ok, why = _compare(out, rec, topic_types.get(tn))
            if not ok:
                return CaseResult(name, file, "FAIL", f"output #{i} on {tn}: {why}")
        # extra outputs beyond expected are a failure too
        for tn, recs in actual_by_topic.items():
            if positions[tn] < len(recs):
                extra = recs[positions[tn]]
                return CaseResult(
                    name, file, "FAIL",
                    f"unexpected extra output on {tn}: key={extra.key!r} "
                    f"value={str(extra.value)[:100]!r}"
                )
        return CaseResult(name, file, "PASS")
    except Exception as e:
        return CaseResult(name, file, "ERROR", f"{type(e).__name__}: {str(e)[:200]}")


def _compare(
    expected: Dict[str, Any], rec: Record, row_type=None
) -> Tuple[bool, str]:
    # exact on-wire text match short-circuits (full-precision decimals in
    # DELIMITED lines would otherwise be parsed into lossy floats)
    if isinstance(expected.get("value"), str) and rec.value == expected["value"]:
        pass_value = True
    else:
        pass_value = False
    # key
    if "key" in expected:
        ek = expected["key"]
        ak = rec.key
        if isinstance(ak, tuple) and len(ak) == 1:
            ak = ak[0]
        if not _values_equal(ek, ak):
            return False, f"key mismatch: expected {ek!r}, got {ak!r}"
    # value
    if not pass_value:
        ev = expected.get("value")
        av = _parse_payload(rec.value)
        if not _values_equal(ev, av, row_type):
            return False, f"value mismatch: expected {ev!r}, got {av!r}"
    # timestamp
    if "timestamp" in expected and expected["timestamp"] is not None:
        if int(expected["timestamp"]) != rec.timestamp:
            return False, (
                f"timestamp mismatch: expected {expected['timestamp']}, got {rec.timestamp}"
            )
    # window
    if "window" in expected and expected["window"] is not None:
        w = expected["window"]
        if rec.window is None:
            return False, "expected windowed record, got unwindowed"
        if int(w["start"]) != rec.window[0]:
            return False, f"window start mismatch: {w['start']} vs {rec.window[0]}"
        if "end" in w and w.get("type", "").upper() == "SESSION":
            if int(w["end"]) != rec.window[1]:
                return False, f"window end mismatch: {w['end']} vs {rec.window[1]}"
    return True, ""


def _expand_matrix(case: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Expand `format`/`config` matrices: every occurrence of {FORMAT} /
    {CONFIG} in statements/names is substituted per combination (the
    reference runner's parameterized-case mechanism)."""
    variants = [case]
    for key, placeholder in (("format", "{FORMAT}"), ("config", "{CONFIG}")):
        if key not in case:
            continue
        expanded = []
        for variant in variants:
            for value in case[key]:
                c = _subst(variant, placeholder, str(value))
                c["name"] = f"{variant.get('name', 'unnamed')} - {key}={value}"
                expanded.append(c)
        variants = expanded
    return variants


def _subst(obj: Any, placeholder: str, value: str) -> Any:
    """Structural deep-copy with placeholder substitution in strings (keeps
    exact Decimal literals intact — a dumps/loads round trip would not)."""
    if isinstance(obj, str):
        return obj.replace(placeholder, value)
    if isinstance(obj, dict):
        return {
            _subst(k, placeholder, value): _subst(v, placeholder, value)
            for k, v in obj.items()
        }
    if isinstance(obj, list):
        return [_subst(v, placeholder, value) for v in obj]
    return obj


def run_file(path: str) -> List[CaseResult]:
    import re as _re

    with open(path) as f:
        text = f.read()
    # the reference loader accepts // comments in test files (attr.json)
    text = _re.sub(r"^\s*//.*$", "", text, flags=_re.M)
    # exact decimals: float literals beyond double precision must survive
    # the corpus load (Jackson parses into BigDecimal)
    import decimal as _dec

    def _pf(s: str):
        d = _dec.Decimal(s)
        f = float(s)
        return d if float(d) != f or _dec.Decimal(repr(f)) != d else f

    doc = json.loads(text, parse_float=_pf)
    out = []
    import os

    base = os.path.basename(path)
    for case in doc.get("tests", ()):
        for variant in _expand_matrix(case):
            out.append(run_case(variant, base))
    return out


def summarize(results: List[CaseResult]) -> Dict[str, int]:
    summary: Dict[str, int] = {}
    for r in results:
        summary[r.status] = summary.get(r.status, 0) + 1
    return summary
