"""User-facing SQL test runner.

Analog of ksqldb-testing-tool (SqlTestingTool.java, driver/TestDriverPipeline
.java, klip-32): runs ``.sql`` files containing test sections delimited by
``--@test:`` comments, executing statements against a fresh engine and
checking ``ASSERT VALUES / ASSERT NULL VALUES / ASSERT STREAM / ASSERT
TABLE`` statements (grammar SqlBase.g4:35,105-110).

Directives (comment lines):
  --@test: <name>               start a new test case
  --@expected.error: <class>    the case must fail
  --@expected.message: <text>   ... with this text in the error
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, List, Optional, Tuple

from ksql_tpu.common.errors import KsqlException
from ksql_tpu.engine.engine import KsqlEngine
from ksql_tpu.parser import ast_nodes as ast


@dataclasses.dataclass
class TestCase:
    name: str
    statements: str
    expected_error: Optional[str] = None
    expected_message: Optional[str] = None


@dataclasses.dataclass
class TestResult:
    name: str
    status: str  # PASS | FAIL | ERROR
    detail: str = ""


def parse_test_file(text: str) -> List[TestCase]:
    cases: List[TestCase] = []
    cur: Optional[TestCase] = None
    buf: List[str] = []

    def flush():
        nonlocal cur, buf
        if cur is not None:
            cur.statements = "\n".join(buf)
            cases.append(cur)
        buf = []

    for line in text.splitlines():
        m = re.match(r"\s*--@test:\s*(.+)", line)
        if m:
            flush()
            cur = TestCase(name=m.group(1).strip(), statements="")
            continue
        m = re.match(r"\s*--@expected\.error:\s*(.+)", line)
        if m and cur:
            cur.expected_error = m.group(1).strip()
            continue
        m = re.match(r"\s*--@expected\.message:\s*(.+)", line)
        if m and cur:
            cur.expected_message = m.group(1).strip()
            continue
        if re.match(r"\s*--", line):
            continue
        if cur is not None:
            buf.append(line)
    flush()
    return cases


class SqlTester:
    """TestDriverPipeline analog: executes one test case."""

    def __init__(self) -> None:
        self.engine = KsqlEngine()
        # per-sink read positions for ASSERT VALUES
        self._positions: Dict[str, int] = {}

    def run_case(self, case: TestCase) -> TestResult:
        try:
            for prepared in self.engine.parse(case.statements):
                self._run_statement(prepared)
        except AssertionError as e:
            if case.expected_error or case.expected_message:
                return self._check_expected(case, e)
            return TestResult(case.name, "FAIL", str(e))
        except Exception as e:  # noqa: BLE001
            if case.expected_error or case.expected_message:
                return self._check_expected(case, e)
            return TestResult(case.name, "ERROR", f"{type(e).__name__}: {e}")
        if case.expected_error or case.expected_message:
            return TestResult(case.name, "FAIL", "expected error not raised")
        return TestResult(case.name, "PASS")

    def _check_expected(self, case: TestCase, e: Exception) -> TestResult:
        if case.expected_message and case.expected_message not in str(e):
            return TestResult(
                case.name, "FAIL",
                f"error message mismatch: wanted {case.expected_message!r}, "
                f"got {str(e)[:120]!r}")
        return TestResult(case.name, "PASS", str(e)[:80])

    # ------------------------------------------------------------ statements
    def _run_statement(self, prepared) -> None:
        s = prepared.statement
        if isinstance(s, ast.AssertValues):
            self._assert_values(s, tombstone=False)
        elif isinstance(s, ast.AssertTombstone):
            self._assert_values(s, tombstone=True)
        elif isinstance(s, (ast.AssertStream, ast.AssertTable)):
            self._assert_source(s)
        elif isinstance(s, ast.RunScript):
            with open(s.path) as f:
                for p2 in self.engine.parse(f.read()):
                    self._run_statement(p2)
        else:
            try:
                self.engine.execute_statement(prepared)
            except KsqlException as e:
                raise KsqlException(
                    f"Exception while preparing statement: {e}"
                ) from e
            self.engine.run_until_quiescent()

    def _assert_source(self, s) -> None:
        inner = s.statement
        src = self.engine.metastore.get_source(inner.name)
        want_table = isinstance(s, ast.AssertTable)
        if src is None:
            raise KsqlException(f"{inner.name} does not exist")
        if src.is_table() != want_table:
            raise KsqlException(
                f"Expected type does not match actual for source {inner.name}. "
                f"Expected: {'TABLE' if want_table else 'STREAM'}, actual: "
                f"{'TABLE' if src.is_table() else 'STREAM'}"
            )
        if inner.elements:
            expected = KsqlEngine.schema_from_elements(inner.elements)
            if expected != src.schema:
                raise KsqlException(
                    f"Expected schema does not match actual for source "
                    f"{inner.name}. Expected: {expected}, actual: {src.schema}"
                )
        props = {k.upper(): v for k, v in inner.properties.items()}

        def check(prop, actual, what, fold_case=True):
            want = props.get(prop)
            if want is None:
                return
            a, b = str(want), str(actual)
            if fold_case:
                a, b = a.upper(), b.upper()
            if a != b:
                raise KsqlException(
                    f"Expected {what} does not match actual for source "
                    f"{inner.name}. Expected: {want}, actual: {actual}"
                )

        check("KAFKA_TOPIC", src.topic, "kafka topic", fold_case=False)
        check("KEY_FORMAT", src.key_format.format, "key format")
        check("VALUE_FORMAT", src.value_format, "value format")
        if "FORMAT" in props:
            check("FORMAT", src.key_format.format, "format")
            check("FORMAT", src.value_format, "format")
        check("TIMESTAMP", src.timestamp_column, "timestamp column")
        check("TIMESTAMP_FORMAT", src.timestamp_format, "timestamp format")

    def _assert_values(self, s, tombstone: bool) -> None:
        self.engine.run_until_quiescent()
        src = self.engine.metastore.get_source(s.source)
        if src is None:
            raise AssertionError(f"{s.source} does not exist")
        topic = self.engine.broker.topic(src.topic)
        pos = self._positions.get(s.source, 0)
        records = topic.all_records()
        if pos >= len(records):
            raise AssertionError(
                f"no record to assert on {s.source} (position {pos})"
            )
        rec = records[pos]
        self._positions[s.source] = pos + 1

        from ksql_tpu.execution.interpreter import ExpressionCompiler, TypeResolver
        from ksql_tpu.serde import formats as fmt

        compiler = ExpressionCompiler(TypeResolver({}), self.engine.registry)
        cols = [c.upper() for c in s.columns] if s.columns else [
            c.name for c in src.schema.columns()
        ]
        vals = [compiler.compile(v)({}) for v in s.values]
        expected = dict(zip(cols, vals))

        key_row = fmt.deserialize_key(
            src.key_format.format, rec.key, src.schema.key_columns
        ) if rec.key is not None else {}
        value_serde = fmt.of(src.value_format, wrap_single_values=src.wrap_single_values)
        value_row = (
            value_serde.deserialize(rec.value, list(src.schema.value_columns))
            if rec.value is not None else None
        )
        if tombstone:
            if value_row is not None:
                raise AssertionError(
                    "Expected record does not match actual: expected tombstone "
                    f"on {s.source}, got {value_row}"
                )
            actual = dict(key_row)
        else:
            if value_row is None:
                raise AssertionError(f"expected row on {s.source}, got tombstone")
            actual = dict(key_row)
            actual.update(value_row)
        actual["ROWTIME"] = rec.timestamp
        if rec.window is not None:
            actual["WINDOWSTART"], actual["WINDOWEND"] = rec.window
        for c in expected:
            if c not in actual:
                raise AssertionError(f"column {c} not in record {actual}")
            if not _eq(expected[c], actual[c]):
                raise AssertionError(
                    f"Expected record does not match actual. {s.source}[{pos}]"
                    f".{c}: expected {expected[c]!r}, got {actual[c]!r}"
                )


def _eq(a: Any, b: Any) -> bool:
    if isinstance(a, str) and isinstance(b, bytes):
        import base64

        return base64.b64decode(a) == b
    if isinstance(a, bool) != isinstance(b, bool):
        return False
    if isinstance(a, float) or isinstance(b, float):
        try:
            return abs(float(a) - float(b)) < 1e-9
        except (TypeError, ValueError):
            return False
    return a == b


def run_test_file(path: str) -> List[TestResult]:
    with open(path) as f:
        cases = parse_test_file(f.read())
    return [SqlTester().run_case(c) for c in cases]


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import sys

    p = argparse.ArgumentParser(prog="ksql-tpu-test-runner")
    p.add_argument("files", nargs="+")
    args = p.parse_args(argv)
    failed = 0
    for path in args.files:
        for r in run_test_file(path):
            mark = "ok" if r.status == "PASS" else "FAIL"
            print(f"[{mark}] {path} :: {r.name} {('- ' + r.detail) if r.detail else ''}")
            if r.status != "PASS":
                failed += 1
    return 1 if failed else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
