"""Golden physical-plan corpus (VERDICT round-3 item 10).

The reference commits 2,097 historical plans
(ksqldb-functional-tests/src/test/resources/historical_plans/) and verifies
on every build that planning the same SQL still produces byte-identical
serialized plans — the upgrade-compatibility discipline for the plan
format (PlannedTestGeneratorUtil / TestCasePlan).  This module does the
same for this engine: for every QTT case whose statements plan cleanly, the
serialized `QueryPlan` JSON of each persistent query is written under
``golden_plans/<case-file>.json`` keyed by case name, and a test replans
and diffs.

Regeneration discipline: a plan diff is a *compatibility decision*, not a
test flake — regenerate with ``python scripts/gen_golden_plans.py`` only
when the plan format intentionally changes, and review the diff.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

QTT_DIR = (
    "/root/reference/ksqldb-functional-tests/src/test/resources/"
    "query-validation-tests"
)
GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "golden_plans")


def plan_case(case: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Plan one QTT case's statements (no data): query-id → plan JSON.

    Returns None when the case can't be planned (expected-exception cases,
    unsupported functions, ...) — those have no golden plan."""
    from ksql_tpu.common.config import RUNTIME_BACKEND, KsqlConfig
    from ksql_tpu.engine.engine import KsqlEngine
    from ksql_tpu.execution.steps import plan_to_json

    if "expectedException" in case:
        return None
    engine = KsqlEngine(KsqlConfig({RUNTIME_BACKEND: "oracle"}))
    engine.session_properties.update(case.get("properties", {}))
    try:
        for t in case.get("topics", ()):
            if isinstance(t, str):
                engine.broker.create_topic(t, 4)
                continue
            engine.broker.create_topic(t["name"], int(t.get("partitions", 4) or 4))
            for kind in ("key", "value"):
                if t.get(f"{kind}Schema") is not None:
                    args = (
                        f"{t['name']}-{kind}",
                        str(t.get(f"{kind}Format", "AVRO")),
                        t[f"{kind}Schema"],
                        tuple(
                            r.get("schema")
                            for r in t.get(f"{kind}SchemaReferences", ())
                        ),
                    )
                    if t.get(f"{kind}SchemaId") is not None:
                        engine.schema_registry.register(
                            *args, schema_id=int(t[f"{kind}SchemaId"])
                        )
                    else:
                        engine.schema_registry.add_pending(*args)
        for rec in case.get("inputs", ()):
            engine.broker.create_topic(rec["topic"], 4)
        for stmt in case.get("statements", ()):
            for prepared in engine.parse(stmt):
                engine.execute_statement(prepared)
    except Exception:
        return None
    return {
        qid: plan_to_json(h.plan) for qid, h in sorted(engine.queries.items())
    }


def generate_file(path: str) -> Tuple[str, Dict[str, Any]]:
    """Golden plans for one QTT corpus file: case name → plans (format
    matrix expanded, as the QTT harness runs them)."""
    import re as _re

    from ksql_tpu.tools.qtt import _expand_matrix

    with open(path) as f:
        text = f.read()
    text = _re.sub(r"^\s*//.*$", "", text, flags=_re.M)
    spec = json.loads(text)
    out: Dict[str, Any] = {}
    for case in spec.get("tests", ()):
        for variant in _expand_matrix(case):
            plans = plan_case(variant)
            if plans:
                out[variant.get("name", "unnamed")] = plans
    return os.path.basename(path), out


def write_golden(fname: str, plans: Dict[str, Any], golden_dir: str = GOLDEN_DIR) -> str:
    os.makedirs(golden_dir, exist_ok=True)
    path = os.path.join(golden_dir, fname)
    with open(path, "w") as f:
        json.dump(plans, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def diff_file(fname: str, golden_dir: str = GOLDEN_DIR) -> List[str]:
    """Replan a corpus file and report divergences from the committed
    golden plans.  Returns a list of human-readable diffs (empty = stable)."""
    golden_path = os.path.join(golden_dir, fname)
    with open(golden_path) as f:
        golden = json.load(f)
    _, fresh = generate_file(os.path.join(QTT_DIR, fname))
    diffs: List[str] = []
    for case, plans in golden.items():
        now = fresh.get(case)
        if now is None:
            diffs.append(f"{case}: no longer plans")
            continue
        if json.loads(json.dumps(now)) != plans:
            diffs.append(f"{case}: plan changed")
    for case in fresh:
        if case not in golden:
            diffs.append(f"{case}: newly planning (regenerate goldens)")
    return diffs


# ----------------------------------------------- static backend classification

#: representative slice for tier-1 sweeps (same breadth rationale as the
#: plan-stability test: projections, aggregates, all join flavors, windows,
#: partition-by, suppress, serde features)
BREADTH_FILES = [
    "project-filter.json",
    "tumbling-windows.json",
    "hopping-windows.json",
    "session-windows.json",
    "joins.json",
    "fk-join.json",
    "partition-by.json",
    "suppress.json",
    "having.json",
    "multi-col-keys.json",
]

SNAPSHOT_PATH = os.path.join(
    os.path.dirname(GOLDEN_DIR), "tests", "backend_snapshot.json"
)


def classify_corpus(
    files: Optional[List[str]] = None,
    backend: str = "distributed",
    deep: bool = True,
    golden_dir: str = GOLDEN_DIR,
) -> Dict[str, Dict[str, Dict[str, Any]]]:
    """Statically classify every golden plan's backend placement:
    file → case → query-id → {backend, reasons}.

    ``deep=True`` runs the real lowering constructor per plan (exact —
    expression-level gaps included) and is what the committed snapshot
    pins; classification under ``backend=distributed`` exercises every
    rung of the ladder."""
    from ksql_tpu.analysis import classify_plan
    from ksql_tpu.execution.steps import plan_from_json
    from ksql_tpu.functions.registry import FunctionRegistry

    registry = FunctionRegistry()
    names = files if files is not None else sorted(os.listdir(golden_dir))
    out: Dict[str, Dict[str, Dict[str, Any]]] = {}
    for fname in names:
        with open(os.path.join(golden_dir, fname)) as f:
            cases = json.load(f)
        per_file: Dict[str, Dict[str, Any]] = {}
        for case, plans in sorted(cases.items()):
            per_case: Dict[str, Any] = {}
            for qid, pj in sorted(plans.items()):
                d = classify_plan(
                    plan_from_json(pj), registry, backend=backend, deep=deep
                )
                per_case[qid] = {
                    "backend": d.backend,
                    "reasons": [f"{rung}: {r}" for rung, r in d.reasons],
                }
                if d.windowing is not None:
                    per_case[qid]["windowing"] = d.windowing
            per_file[case] = per_case
        out[fname] = per_file
    return out
