"""Schema migrations tool — the ksql-migrations analog.

Reference: ksqldb-tools/src/main/java/io/confluent/ksql/tools/migrations/
(Migrations.java:70, commands New/Create/Apply/Info/Validate/Initialize).
Versioned ``V000001__description.sql`` files apply in order against a
server; applied versions are recorded durably in the MIGRATION_EVENTS
stream and the MIGRATION_SCHEMA_VERSIONS table, so every node (and every
restart) agrees on the current schema version and edits to already-applied
files are detected by checksum.

Usage (CLI)::

    python -m ksql_tpu.tools.migrations new <project-dir> <server-url>
    python -m ksql_tpu.tools.migrations create <desc> -d <project-dir>
    python -m ksql_tpu.tools.migrations initialize -d <project-dir>
    python -m ksql_tpu.tools.migrations apply -a -d <project-dir>
    python -m ksql_tpu.tools.migrations info -d <project-dir>
    python -m ksql_tpu.tools.migrations validate -d <project-dir>
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import re
import time
from typing import Any, Dict, List, Optional

MIGRATIONS_DIR = "migrations"
CONFIG_FILE = "ksql-migrations.properties"
EVENTS_STREAM = "MIGRATION_EVENTS"
VERSIONS_TABLE = "MIGRATION_SCHEMA_VERSIONS"
_FILE_RE = re.compile(r"V(\d{6})__(.+)\.sql$")


@dataclasses.dataclass
class Migration:
    version: int
    name: str
    path: str

    @property
    def checksum(self) -> str:
        with open(self.path, "rb") as f:
            return hashlib.md5(f.read()).hexdigest()


def scan_migrations(project_dir: str) -> List[Migration]:
    mdir = os.path.join(project_dir, MIGRATIONS_DIR)
    out: List[Migration] = []
    if not os.path.isdir(mdir):
        return out
    for fname in sorted(os.listdir(mdir)):
        m = _FILE_RE.fullmatch(fname)
        if m:
            out.append(
                Migration(
                    version=int(m.group(1)),
                    name=m.group(2).replace("_", " "),
                    path=os.path.join(mdir, fname),
                )
            )
    versions = [m.version for m in out]
    if len(set(versions)) != len(versions):
        raise ValueError(f"duplicate migration versions in {mdir}")
    return out


def new_project(project_dir: str, server_url: str) -> str:
    """``migrations new``: scaffold the project directory + config."""
    os.makedirs(os.path.join(project_dir, MIGRATIONS_DIR), exist_ok=True)
    cfg = os.path.join(project_dir, CONFIG_FILE)
    if not os.path.exists(cfg):
        with open(cfg, "w") as f:
            f.write(f"ksql.server.url={server_url}\n")
    return cfg


def create_migration(project_dir: str, description: str) -> str:
    """``migrations create``: next-version empty migration file."""
    existing = scan_migrations(project_dir)
    version = (existing[-1].version + 1) if existing else 1
    slug = re.sub(r"[^A-Za-z0-9]+", "_", description).strip("_")
    path = os.path.join(
        project_dir, MIGRATIONS_DIR, f"V{version:06d}__{slug}.sql"
    )
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(f"-- migration {version}: {description}\n")
    return path


def read_server_url(project_dir: str) -> str:
    with open(os.path.join(project_dir, CONFIG_FILE)) as f:
        for line in f:
            if line.startswith("ksql.server.url="):
                return line.split("=", 1)[1].strip()
    raise ValueError(f"no ksql.server.url in {project_dir}/{CONFIG_FILE}")


class MigrationsClient:
    """Statement runner + metadata access over the REST client."""

    def __init__(self, server_url: str):
        from ksql_tpu.client.client import KsqlRestClient

        self.client = KsqlRestClient(server_url)

    # ------------------------------------------------------------ metadata
    def initialize(self) -> None:
        """``migrations initialize``: create the metadata stream + table
        (InitializeMigrationCommand)."""
        self.client.make_ksql_request(
            f"CREATE STREAM IF NOT EXISTS {EVENTS_STREAM} ("
            "  version_key STRING KEY,"
            "  version STRING,"
            "  name STRING,"
            "  state STRING,"
            "  checksum STRING,"
            "  started_on STRING,"
            "  completed_on STRING,"
            "  previous STRING"
            ") WITH (KAFKA_TOPIC='default_ksql_MIGRATION_EVENTS', "
            "VALUE_FORMAT='JSON', PARTITIONS=1);"
        )
        self.client.make_ksql_request(
            f"CREATE TABLE IF NOT EXISTS {VERSIONS_TABLE} AS "
            f"SELECT version_key, "
            "  LATEST_BY_OFFSET(version) AS version, "
            "  LATEST_BY_OFFSET(name) AS name, "
            "  LATEST_BY_OFFSET(state) AS state, "
            "  LATEST_BY_OFFSET(checksum) AS checksum, "
            "  LATEST_BY_OFFSET(started_on) AS started_on, "
            "  LATEST_BY_OFFSET(completed_on) AS completed_on, "
            "  LATEST_BY_OFFSET(previous) AS previous "
            f"FROM {EVENTS_STREAM} GROUP BY version_key;"
        )

    def _record(self, version: int, name: str, state: str, checksum: str,
                started: str, completed: str, previous: str) -> None:
        for key in (str(version), "CURRENT"):
            self.client.make_ksql_request(
                f"INSERT INTO {EVENTS_STREAM} ("
                "version_key, version, name, state, checksum, started_on, "
                "completed_on, previous) VALUES ("
                f"'{key}', '{version}', '{name}', '{state}', '{checksum}', "
                f"'{started}', '{completed}', '{previous}');"
            )

    def version_info(self, version_key: str) -> Optional[Dict[str, Any]]:
        res = self.client.make_query_request(
            f"SELECT * FROM {VERSIONS_TABLE} "
            f"WHERE version_key = '{version_key}';"
        )
        rows = res.get("rows") or []
        if not rows:
            return None
        cols = [c.upper() for c in res.get("columnNames") or res.get("columns") or []]
        return dict(zip(cols, rows[0])) if isinstance(rows[0], list) else {
            k.upper(): v for k, v in rows[0].items()
        }

    def current_version(self) -> int:
        info = self.version_info("CURRENT")
        if info is None or info.get("STATE") not in ("MIGRATED",):
            # an ERROR current version blocks forward progress until fixed
            if info is not None and info.get("STATE") == "ERROR":
                raise RuntimeError(
                    f"current version {info.get('VERSION')} is in ERROR state; "
                    "fix and re-apply before migrating further"
                )
            return int(info["VERSION"]) if info else 0
        return int(info["VERSION"])

    # --------------------------------------------------------------- apply
    def apply(self, project_dir: str, until: Optional[int] = None,
              next_only: bool = False) -> List[int]:
        """``migrations apply``: run pending migrations in order, recording
        RUNNING → MIGRATED/ERROR events per version."""
        migrations = scan_migrations(project_dir)
        current = self.current_version()
        pending = [m for m in migrations if m.version > current]
        if until is not None:
            pending = [m for m in pending if m.version <= until]
        if next_only:
            pending = pending[:1]
        applied: List[int] = []
        previous = str(current) if current else "<none>"
        for m in pending:
            started = time.strftime("%Y-%m-%dT%H:%M:%S")
            checksum = m.checksum
            self._record(m.version, m.name, "RUNNING", checksum, started, "", previous)
            try:
                with open(m.path) as f:
                    sql = f.read()
                if sql.strip():
                    self.client.make_ksql_request(sql)
            except Exception:
                self._record(
                    m.version, m.name, "ERROR", checksum, started,
                    time.strftime("%Y-%m-%dT%H:%M:%S"), previous,
                )
                raise
            self._record(
                m.version, m.name, "MIGRATED", checksum, started,
                time.strftime("%Y-%m-%dT%H:%M:%S"), previous,
            )
            previous = str(m.version)
            applied.append(m.version)
        return applied

    # ---------------------------------------------------------------- info
    def info(self, project_dir: str) -> List[Dict[str, Any]]:
        out = []
        current = self.current_version()
        for m in scan_migrations(project_dir):
            vi = self.version_info(str(m.version))
            out.append({
                "version": m.version,
                "name": m.name,
                "state": (vi or {}).get("STATE", "PENDING"),
                "is_current": m.version == current,
            })
        return out

    def validate(self, project_dir: str) -> List[str]:
        """``migrations validate``: checksum drift on applied files."""
        problems = []
        for m in scan_migrations(project_dir):
            vi = self.version_info(str(m.version))
            if vi and vi.get("STATE") == "MIGRATED" and vi.get("CHECKSUM") != m.checksum:
                problems.append(
                    f"V{m.version:06d} was modified after being applied "
                    f"(checksum {m.checksum} != {vi.get('CHECKSUM')})"
                )
        return problems


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="ksql-migrations")
    sub = p.add_subparsers(dest="cmd", required=True)
    s_new = sub.add_parser("new")
    s_new.add_argument("project_dir")
    s_new.add_argument("server_url")
    s_create = sub.add_parser("create")
    s_create.add_argument("description")
    s_create.add_argument("-d", "--project-dir", default=".")
    for name in ("initialize", "info", "validate"):
        s = sub.add_parser(name)
        s.add_argument("-d", "--project-dir", default=".")
    s_apply = sub.add_parser("apply")
    s_apply.add_argument("-d", "--project-dir", default=".")
    s_apply.add_argument("-a", "--all", action="store_true")
    s_apply.add_argument("-n", "--next", action="store_true")
    s_apply.add_argument("-u", "--until", type=int)
    args = p.parse_args(argv)

    if args.cmd == "new":
        print(new_project(args.project_dir, args.server_url))
        return 0
    if args.cmd == "create":
        print(create_migration(args.project_dir, args.description))
        return 0
    mc = MigrationsClient(read_server_url(args.project_dir))
    if args.cmd == "initialize":
        mc.initialize()
        print("migration metadata initialized")
    elif args.cmd == "apply":
        applied = mc.apply(
            args.project_dir, until=args.until, next_only=args.next
        )
        print(f"applied versions: {applied or 'none'}")
    elif args.cmd == "info":
        for row in mc.info(args.project_dir):
            cur = " (current)" if row["is_current"] else ""
            print(f"V{row['version']:06d} {row['state']:<9} {row['name']}{cur}")
    elif args.cmd == "validate":
        problems = mc.validate(args.project_dir)
        for pr in problems:
            print(pr)
        return 1 if problems else 0
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
