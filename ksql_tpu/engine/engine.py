"""Engine: statement lifecycle — parse, analyze, plan, execute.

Analog of ksqldb-engine's KsqlEngine (KsqlEngine.java:104: parse():285,
prepare():290, plan():298, execute():308, executeTransientQuery():343) plus
the query registry (QueryRegistryImpl.java:68).  Persistent queries run on
the XLA device backend when the plan lowers (DeviceExecutor, the
KSPlanBuilder-seam analog) and fall back to the row oracle otherwise,
selected by ``ksql.runtime.backend``; the engine also serves pull queries
from sink materializations.
"""

from __future__ import annotations

import dataclasses
import itertools
import os
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ksql_tpu.common import health as qhealth
from ksql_tpu.common import tracing
from ksql_tpu.common.config import KsqlConfig
from ksql_tpu.common.errors import AnalysisException, KsqlException, PlanningException
from ksql_tpu.common.schema import LogicalSchema
from ksql_tpu.analyzer.analyzer import analyze_query
from ksql_tpu.execution import steps as st
from ksql_tpu.execution.interpreter import ExpressionCompiler, TypeResolver, make_caster
from ksql_tpu.functions.registry import FunctionRegistry, default_registry
from ksql_tpu.metastore.metastore import (
    DataSource,
    DataSourceType,
    KeyFormat,
    MetaStore,
)
from ksql_tpu.parser import ast_nodes as ast
from ksql_tpu.parser.parser import parse_statements
from ksql_tpu.planner.logical import LogicalPlanner, PlannedQuery
from ksql_tpu.runtime.oracle import OracleExecutor, SinkEmit
from ksql_tpu.common import config as cfg
from ksql_tpu.runtime.topics import Broker, Consumer, Record


@dataclasses.dataclass
class QueryError:
    """One classified query error (reference QueryError + type enum)."""

    timestamp_ms: int
    message: str
    error_type: str  # USER | SYSTEM | UNKNOWN


def _marker_hit(text: str, markers) -> bool:
    """Case-insensitive marker match.  Single-word markers require a
    leading word boundary — a plain substring check made 'broadcast' trip
    the 'cast' USER rule.  Only the LEADING edge is bounded so markers
    still match as CamelCase prefixes ('overflow' in OverflowError, 'XLA'
    in XlaRuntimeError) and as stems ('deserialize' in deserialization).
    Multi-word markers ('does not exist') stay substrings."""
    import re as _re

    for m in markers:
        if " " in m:
            if m.lower() in text.lower():
                return True
        elif _re.search(rf"(?<![A-Za-z0-9]){_re.escape(m)}", text, _re.IGNORECASE):
            return True
    return False


def classify_error(e: Exception, custom_rules: str = "") -> str:
    """QueryErrorClassifier chain analog: built-in classifiers
    (RegexClassifier, MissingTopicClassifier, ...) fold to one verdict;
    ksql.error.classifier.regex supplies extra 'TYPE:regex' rules
    (semicolon-separated)."""
    import re as _re

    text = f"{type(e).__name__}: {e}"
    for rule in str(custom_rules or "").split(";"):
        rule = rule.strip()
        if not rule or ":" not in rule:
            continue
        etype, pattern = rule.split(":", 1)
        try:
            if _re.search(pattern, text):
                return etype.strip().upper()
        except _re.error:
            continue
    from ksql_tpu.common.faults import FaultInjected

    if isinstance(e, FaultInjected):
        # injected faults model infrastructure failures, whatever their
        # message mentions (a serde-point fault contains 'deserialize',
        # which would otherwise win the USER check below)
        return "SYSTEM"
    user_markers = (
        "SerdeException", "deserialize", "FunctionException", "cast",
        "arithmetic", "Decimal", "overflow", "JSONDecodeError",
    )
    system_markers = ("Topic", "does not exist", "OSError", "IOError",
                      "MemoryError", "XLA", "FaultInjected")
    if _marker_hit(text, user_markers):
        return "USER"
    if _marker_hit(text, system_markers):
        return "SYSTEM"
    return "UNKNOWN"


@dataclasses.dataclass
class QueryHandle:
    """PersistentQueryMetadata analog."""

    query_id: str
    plan: st.QueryPlan
    sink_name: Optional[str]
    executor: Any  # OracleExecutor | DeviceExecutor
    consumer: Consumer
    state: str = "RUNNING"  # RUNNING | PAUSED | TERMINATED | ERROR
    sql: str = ""
    backend: str = "oracle"  # which runtime executes this query
    # sink materialization for pull queries and standby promotion:
    # key -> (row, window, key, emit_ts)
    materialized: Dict[Any, Tuple] = dataclasses.field(default_factory=dict)
    # scalable-push subscribers: called with each SinkEmit as it happens
    # (ScalablePushRegistry/ProcessingQueue analog)
    push_listeners: List[Callable] = dataclasses.field(default_factory=list)
    # batch-level push subscribers (fused tap residuals, ISSUE 12): called
    # once per decoded emission batch with (emits, raw_block) BEFORE the
    # per-emit fan-out, where raw_block carries the still-device-resident
    # columnar emit arrays when this query runs on the device backend —
    # the shared push pipeline feeds its residual kernel from them instead
    # of re-encoding host rows
    push_batch_listeners: List[Callable] = dataclasses.field(
        default_factory=list
    )
    # classified error queue (QueryMetadata.getQueryErrors, bounded by
    # ksql.query.error.max.queue.size) + restart backoff bookkeeping
    error_queue: List[QueryError] = dataclasses.field(default_factory=list)
    retry_at_ms: float = 0.0
    retry_backoff_ms: float = 0.0
    # self-healing bookkeeping: restarts attempted so far, and the terminal
    # flag set once ksql.query.retry.max is exhausted (no further restarts;
    # /healthcheck flips unhealthy and /metrics carries the counts)
    restart_count: int = 0
    terminal: bool = False
    # standby replica: keeps consuming/materializing but publishes nothing
    # (shared-data-plane num.standby.replicas analog)
    standby: bool = False
    # progress tracker + stall watchdog (common/health.py): per-partition
    # offsets/lag, event-time watermark, e2e latency, bounded sample ring
    progress: Optional[qhealth.QueryProgress] = None
    # processing-epoch bookkeeping (ksql.commit.per.record): the durable
    # commit point the current tick has reached, the state epoch matching
    # it (record-synchronous backends), records to drop on replay
    # (poison replay-without-record), and the replay/deadline counters
    # surfaced in /metrics
    commit_positions: Optional[Dict[Tuple[str, int], int]] = None
    epoch: Optional[Dict[str, Any]] = None
    poison_skip: set = dataclasses.field(default_factory=set)
    replayed_records: int = 0
    tick_deadlines: int = 0
    # non-attributable-poison bisection: when a deterministic USER error
    # hides inside a batched device flush (buffered records from earlier
    # process() calls), each re-crash halves the records the next tick may
    # poll ({"limit": n}) until the window is ONE record — which IS
    # attributable and gets skipped atomically via poison_skip.  Cleared by
    # the first clean tick.  Bounded by ksql.query.retry.max like any
    # crash-loop.
    poison_bisect: Optional[Dict[str, Any]] = None
    # elastic-mesh bookkeeping (health-driven live rescale): a per-query
    # shard-count override the next executor (re)build honors, the
    # in-flight cutover descriptor, verdict streaks feeding the
    # hysteresis, the cooldown clock, and completed cutovers per direction
    # (ksql_query_reshard_total{direction})
    shard_override: Optional[int] = None
    pending_rescale: Optional[Dict[str, Any]] = None
    rescale_lag_streak: int = 0
    rescale_idle_streak: int = 0
    last_rescale_ms: float = 0.0
    # cooldown multiplier, doubled on every REVERTED cutover (a reshard the
    # state has proven it cannot perform must not re-pay checkpoint + two
    # recompiles every plain cooldown forever); reset by a completed one
    rescale_penalty: int = 0
    reshard_total: Dict[str, int] = dataclasses.field(default_factory=dict)
    # mesh fault domain (shard-level failure containment): consecutive
    # strikes per shard (reset by any clean tick), lifetime strike totals
    # (ksql_query_shard_strikes_total{shard}), the query's ORIGINAL shard
    # width while running degraded (None = not degraded; the regrow probe
    # restores it once the fault clears), and the wall clock of the last
    # strike (the regrow cooldown's "fault cleared" evidence)
    shard_strikes: Dict[int, int] = dataclasses.field(default_factory=dict)
    shard_strikes_total: Dict[int, int] = dataclasses.field(
        default_factory=dict
    )
    mesh_degraded_from: Optional[int] = None
    last_shard_strike_ms: float = 0.0
    # emit fence: a kill switch captured by the CURRENT executor's emit
    # callback; revoked at the deadline fence and on every executor
    # rebuild, so an abandoned zombie worker that already holds the old
    # callback reference can never write stale materialized rows or wake
    # push listeners (closes the TOCTOU left by nulling emit_callback)
    emit_fence: Optional[Dict[str, bool]] = None
    # rebuild fence (ksql.query.rebuild.timeout.ms): identity token bound
    # at the start of each supervised executor rebuild; the deadline
    # handler swaps it, so an abandoned rebuild worker (hung XLA compile
    # that later wakes) fails its alive() test and can never install its
    # executor, swap the emit fence, or touch family registrations
    rebuild_token: Optional[object] = None
    rebuild_deadlines: int = 0
    # memoized EXPLAIN classification: (classification-input key, decision)
    # — the plan never changes after creation, so the deep lowering probe
    # runs at most once per effective-config combination
    static_decision: Optional[Tuple[Tuple[str, bool], Any]] = None
    # static device-memory footprint report (analysis/mem_model), computed
    # once at admission: feeds EXPLAIN's 'Device memory (static)' table and
    # the ksql_query_estimated_hbm_bytes{point} gauge.  None = the plan
    # does not lower to the device backend (no modeled HBM)
    mem_report: Optional[Any] = None
    # multi-query optimizer verdict (planner/mqo.MqoDecision) from this
    # query's last build: the cost model's accept/reject reasoning EXPLAIN
    # prints.  None = no shared pipeline was in scope at build time
    mqo_decision: Optional[Any] = None
    # overload-manager shedding order (ksql.query.priority, higher = more
    # important): under source pacing, below-top-tier queries are clamped
    # harder.  Captured at CREATE from the effective config.
    priority: int = 100

    def is_running(self) -> bool:
        return self.state == "RUNNING"

    @property
    def health(self) -> str:
        return self.progress.health if self.progress is not None else qhealth.IDLE


#: sentinel for "expression is not a literal" in pull-constraint analysis
#: (None is a real value: WHERE key = NULL)
_NO_LITERAL = object()

#: fallback_reasons entry for a distributed query whose source the C++
#: ingest tier could decode single-device but whose executor kept the
#: Python HostBatch path.  Since the mesh-aware lane split landed
#: (DistributedDeviceQuery.process_columns) eligible plans engage the
#: native tier directly, so this counter staying at zero is itself a
#: pinned invariant; the constant remains for dashboards and the
#: regression test that asserts it no longer fires
NATIVE_INGEST_BYPASS_REASON = (
    "native C++ ingest bypassed in distributed mode; rows decode via "
    "the shared Python path"
)

#: EXPLAIN ``Backend (static)`` note for a distributed placement whose
#: source the C++ ingest tier batch-decodes (the static classifier in
#: analysis/plan_verifier surfaces it; the runtime counter is
#: ksql_native_ingest_rows_total)
NATIVE_INGEST_ENGAGED_NOTE = (
    "native C++ ingest engaged (mesh-aware lane split inside the batch "
    "decoder)"
)


@dataclasses.dataclass
class StatementResult:
    kind: str  # 'ddl' | 'query' | 'rows' | 'ok'
    message: str = ""
    query_id: Optional[str] = None
    rows: Optional[List[dict]] = None
    columns: Optional[List[str]] = None


def _parse_wrap(raw) -> bool:
    """The one boolean parse for WRAP_SINGLE_VALUE (shared by schema
    inference and serde validation so they always agree)."""
    return raw if isinstance(raw, bool) else str(raw).strip().lower() == "true"


def _validate_wrap_property(raw, value_format: str, value_columns) -> Optional[bool]:
    """WRAP_SINGLE_VALUE property validation (SerdeFeaturesFactory
    .getValueWrapping): only single-field schemas, only formats where
    wrapping is configurable."""
    if raw is None:
        return None
    from ksql_tpu.serde import formats as _fmt

    wrap = _parse_wrap(raw)
    f = value_format.upper()
    supported = _fmt.WRAPPABLE if wrap else _fmt.UNWRAPPABLE_VALUES
    if f not in supported:
        raise KsqlException(
            f"Format '{f}' does not support 'WRAP_SINGLE_VALUE' set to "
            f"'{str(wrap).lower()}'."
        )
    if len(list(value_columns)) != 1:
        raise KsqlException(
            "'WRAP_SINGLE_VALUE' is only valid for single-field value schemas"
        )
    return wrap


def _parses_unwrapped(raw) -> bool:
    """True when WRAP_SINGLE_VALUE is explicitly set and parses false."""
    return raw is not None and not _parse_wrap(raw)


def _avro_nested_defaults(prefix: tuple, avro_type) -> list:
    """(path, default) for every non-optional Avro record field below
    ``avro_type`` that declares a schema default — a null written at that
    path is replaced by the default (Connect AvroData substitution)."""
    out: list = []

    def is_null(b):
        return b == "null" or (isinstance(b, dict) and b.get("type") == "null")

    def walk(path, t):
        if isinstance(t, list):
            for b in t:
                if not is_null(b):
                    walk(path, b)
            return
        if isinstance(t, dict) and t.get("type") == "record":
            for f in t.get("fields", ()):
                ft = f["type"]
                nullable = isinstance(ft, list) and any(is_null(b) for b in ft)
                if "default" in f and not nullable:
                    out.append((path + (f["name"],), f["default"]))
                walk(path + (f["name"],), ft)

    walk(prefix, avro_type)
    return out


def _schemas_compatible(query_schema, target_schema) -> bool:
    """INSERT INTO schema check: equal, or each query column implicitly
    coerces to the target column (numeric widening INT -> BIGINT ->
    DECIMAL -> DOUBLE; reference DefaultSqlValueCoercer.canImplicitlyCast)."""
    from ksql_tpu.common.types import SqlBaseType as B

    order = {B.INTEGER: 0, B.BIGINT: 1, B.DECIMAL: 2, B.DOUBLE: 3}

    def ok(src, dst) -> bool:
        if src == dst:
            return True
        sb, db = src.base, dst.base
        if sb in order and db in order and order[sb] <= order[db]:
            return True
        return False

    for group in ("key_columns", "value_columns"):
        qs, ts = list(getattr(query_schema, group)), list(getattr(target_schema, group))
        if len(qs) != len(ts):
            return False
        for q, t in zip(qs, ts):
            if q.name != t.name or not ok(q.type, t.type):
                return False
    return True


class _TickSupervisionWorker:
    """Persistent per-query tick-supervision worker.

    The deadline supervisor submits each non-empty tick body here instead
    of spawning a thread per tick (the ~50–100µs per-tick spawn the
    ROADMAP flagged).  The submitting poll loop blocks on the done event —
    worker and supervisor stay serialized exactly like the joined per-tick
    workers this replaces — or abandons at the deadline, after which the
    worker finishes its hung tick as a fenced zombie (the tick body's own
    ``alive()``/emit-fence guards mute its late writes) and EXITS: it must
    never pick up a later tick whose fences it predates."""

    def __init__(self, query_id: str):
        import queue

        self._q: Any = queue.Queue()
        self._abandoned = False
        self.thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"tick-supervision-{query_id}",
        )
        self.thread.start()

    # graftlint: entrypoint=tick-supervision
    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            fn, done = item
            try:
                fn()
            finally:
                done.set()
            if self._abandoned:
                return

    def submit(self, fn) -> threading.Event:
        done = threading.Event()
        self._q.put((fn, done))
        return done

    def alive(self) -> bool:
        return self.thread.is_alive() and not self._abandoned

    def abandon(self) -> None:
        """Deadline blown: mark the worker a zombie.  The sentinel wakes a
        worker that already finished the hung tick and is idle-blocked on
        the queue, so abandoned workers always exit instead of leaking."""
        # single-writer set-once flag: only the supervising poll loop ever
        # writes it, the worker only reads it between tasks
        self._abandoned = True  # graftlint: owner=main
        self._q.put(None)

    def stop(self, join_timeout_s: float = 1.0) -> None:
        """Terminate path: shut the worker down and join it (a worker
        still wedged inside a hung tick can't be joined — bounded wait)."""
        self._abandoned = True  # graftlint: owner=main
        self._q.put(None)
        self.thread.join(join_timeout_s)


class KsqlEngine:
    def __init__(
        self,
        config: Optional[KsqlConfig] = None,
        broker: Optional[Broker] = None,
        registry: Optional[FunctionRegistry] = None,
    ):
        self.config = config or KsqlConfig()
        # arm the chaos layer before any topic/serde/executor exists so
        # every seam (including cached serdes) sees the fault proxy;
        # idempotent per spec, so engine forks don't reset one-shot rules
        from ksql_tpu.common import faults as _faults

        _faults.install_from_config(
            str(self.config.get(cfg.FAULT_INJECTION_RULES) or "")
        )
        self.broker = broker or Broker()
        self.registry = registry or default_registry()
        if registry is None:
            # UserFunctionLoader.java:45 analog: scan ksql.extension.dir for
            # decorator-declared functions; registered into a per-engine
            # registry fork so extensions never leak into the process-wide
            # built-in registry (sandboxes share the fork via registry=)
            ext_dir = str(self.config.get(cfg.EXTENSION_DIR) or "")
            if ext_dir and os.path.isdir(ext_dir):
                from ksql_tpu.functions.loader import load_extensions

                fork = self.registry.copy()
                if load_extensions(ext_dir, fork):
                    self.registry = fork
        from ksql_tpu.serde.schema_registry import SchemaRegistry

        self.schema_registry = SchemaRegistry()
        self.metastore = MetaStore()
        self.planner = LogicalPlanner(self.registry)
        self.queries: Dict[str, QueryHandle] = {}
        self.variables: Dict[str, str] = {}
        self.session_properties: Dict[str, Any] = {}
        self._query_seq = itertools.count(1)
        self._lock = threading.RLock()
        self.processing_log: List[Tuple[str, str]] = []
        # queries actually running on the XLA backend (vs oracle fallback)
        self.device_query_count = 0
        # of those, queries sharded across the device mesh (backend=
        # distributed); a distribution gap that fell back single-device
        # counts under device_query_count instead
        self.distributed_query_count = 0
        # True on engine forks used for pre-execution validation
        self.is_sandbox = False
        from ksql_tpu.common.metrics import MetricCollectors

        self.metrics = MetricCollectors()
        # why plans fell back to the oracle (reason -> count); surfaced by
        # scripts/device_coverage.py, /metrics (fallback-reasons), and
        # useful for lowering roadmaps.  Windowing-shape fallbacks (a
        # hopping query silently keeping the k-fold expansion path instead
        # of slicing) count here too, so they are observable.
        self.fallback_reasons: Dict[str, int] = {}
        # multi-query-optimizer sharing registries: window-family signature
        # (correlated signature under ksql.optimizer.mqo.enabled, exact
        # family signature otherwise) -> primary query id; source-prefix
        # signature -> primary query id; and member query id -> its
        # primary — both kinds — (engine-level view of
        # CompiledDeviceQuery.attach_member / attach_prefix_member)
        self.window_families: Dict[tuple, str] = {}
        self.prefix_pipelines: Dict[tuple, str] = {}
        self.family_members: Dict[str, str] = {}
        # MQO observability: runtime attach refusals + cost-model rejects
        # per stable reason code (ksql_query_family_attach_refused_total
        # {reason}) and cost-model verdicts (ksql_mqo_decisions_total
        # {verdict})
        self.family_attach_refused: Dict[str, int] = {}
        self.mqo_decisions: Dict[str, int] = {}
        # flight recorders (common/tracing.py): per-query ring buffers of
        # recent tick traces, engine-owned so concurrent engines in one
        # process never share trace state.  Feeds EXPLAIN ANALYZE, the
        # /query-trace/<id> endpoint, and the Prometheus /metrics stage
        # histograms.
        self.trace_enabled = cfg._bool(self.config.get(cfg.TRACE_ENABLE, True))
        self.trace_ring = int(self.config.get(cfg.TRACE_RING_SIZE, 64))
        self.trace_recorders: Dict[str, tracing.FlightRecorder] = {}
        # entries trimmed off the processing-log ring so far (the ring is
        # bounded by ksql.processing.log.buffer.size, cached here — the
        # append sits on the per-record error path); /metrics surfaces it
        self.plog_dropped = 0
        self._plog_cap = int(
            self.config.get(cfg.PROCESSING_LOG_BUFFER_SIZE, 10000)
        )
        # supervised push-query sessions (server/rest.py) report their
        # self-healing restarts here so /metrics carries the counter
        self.push_session_restarts = 0
        # persistent per-query tick-supervision workers (amortize the
        # per-tick thread spawn); abandoned workers are replaced, stopped
        # workers joined on TERMINATE; deadline-abandoned zombies are
        # remembered so shutdown() can give them a bounded join too
        self._tick_workers: Dict[str, _TickSupervisionWorker] = {}
        self._abandoned_workers: List[_TickSupervisionWorker] = []
        # push registry (tentpole): shared serving pipelines multiplexing
        # compatible push sessions as filtered taps.  Lazily built by
        # get_push_registry so engines that never serve push queries pay
        # nothing; metrics_snapshot and shutdown() read it when present.
        self.push_registry: Optional[Any] = None
        # overload manager (engine/overload.py): resource-pressure
        # monitors -> OK/ELEVATED/CRITICAL -> prioritized degradation
        # ladder.  Cheap to construct (no thread); sampling piggybacks on
        # poll_once, server mode adds a dedicated monitor thread.
        from ksql_tpu.engine.overload import OverloadManager

        self.overload = OverloadManager(self)
        # telemetry timelines (common/timeline.py): retained per-query /
        # per-pipeline interval series folded from finished tick traces
        # via the flight-recorder observer.  Lazily built per owner; the
        # skew detector's verdicts drain into telemetry_events for the
        # /alerts "telemetry" section (note_event evidence only surfaces
        # for LAGGING/STALLED queries — a skewed-but-healthy query must
        # still alert).
        self.telemetry_enabled = cfg._bool(
            self.config.get(cfg.TELEMETRY_ENABLE, True)
        )
        self.timelines: Dict[str, Any] = {}
        self.telemetry_events: deque = deque(maxlen=32)
        # incremental changelog journals (runtime/changelog.py): one per
        # journaled query, chained to the checkpoint generation id below.
        # None until a generation exists — frames need a base snapshot.
        self._changelogs: Dict[str, Any] = {}
        self._ckpt_id: Optional[str] = None
        # per-query wall time of the last fresh snapshot
        # (ksql_checkpoint_age_seconds)
        self._checkpoint_saved_at: Dict[str, float] = {}
        # queries already noted as seam-less (changelog.skip is loud ONCE)
        self._changelog_skip_noted: set = set()
        # raised when a journal passes ksql.changelog.max.bytes; the next
        # poll-loop gate checkpoints early (rotation truncates the file)
        self._changelog_force_ckpt = False

    def timeline_store(self, owner_id: str):
        """Lazy per-owner TimelineStore (owner = query id or push
        pipeline id), config-shaped once at creation."""
        tl = self.timelines.get(owner_id)
        if tl is None:
            from ksql_tpu.common.timeline import TimelineStore

            tl = self.timelines[owner_id] = TimelineStore(
                owner_id,
                interval_ms=int(
                    self.config.get(cfg.TELEMETRY_INTERVAL_MS, 5000)
                ),
                ring=int(
                    self.config.get(cfg.TELEMETRY_RING_INTERVALS, 240)
                ),
                skew_ratio=float(
                    self.config.get(cfg.TELEMETRY_SKEW_RATIO, 1.8)
                ),
                skew_intervals=int(
                    self.config.get(cfg.TELEMETRY_SKEW_INTERVALS, 3)
                ),
            )
        return tl

    def trace_recorder(self, query_id: str) -> tracing.FlightRecorder:
        rec = self.trace_recorders.get(query_id)
        if rec is None:
            rec = self.trace_recorders[query_id] = tracing.FlightRecorder(
                query_id, self.trace_ring
            )
            if self.telemetry_enabled:
                # retention hook: every recorded tick (queries AND push
                # pipeline pumps — both create recorders through here)
                # folds into the owner's timeline
                rec.observer = self.timeline_store(query_id).fold
        return rec

    def recorder_if_enabled(
        self, query_id: str
    ) -> Optional[tracing.FlightRecorder]:
        """The query's flight recorder, or None when tracing is off —
        the guard every `with tracing.tick(...)` site needs."""
        return self.trace_recorder(query_id) if self.trace_enabled else None

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Engine + per-query gauges (KsqlEngineMetrics analog)."""
        return self.metrics.snapshot(engine=self)

    def annotate_serde_semantics(self, plan: st.QueryPlan) -> None:
        """Attach metastore-held serde semantics (PROTOBUF nullable
        representation, float32 fields) to the plan's source/sink steps as
        runtime annotations — plan JSON stays format-stable."""
        for step in st.walk_steps(plan.physical_plan):
            src_name = getattr(step, "source_name", None)
            target = None
            if src_name:
                target = self.metastore.get_source(src_name)
            elif isinstance(step, (st.StreamSink, st.TableSink)) and plan.sink_name:
                target = self.metastore.get_source(plan.sink_name)
            if target is None:
                continue
            if getattr(target, "proto_nullable_rep", None):
                step.__dict__["_proto_nullable_all"] = True
            if getattr(target, "proto_float32", ()):
                step.__dict__["_proto_float32"] = tuple(target.proto_float32)

    # ------------------------------------------------------- scalable push
    def register_push_tap(
        self, source_name: str, cb, batch_cb=None
    ) -> Optional[Tuple[str, Callable]]:
        """Push-registry seam: attach a subscriber to the RUNNING
        persistent query materializing ``source_name`` — the fan-out rides
        the query's fence-guarded ``on_emit`` (PR-6 zombie fencing and the
        PR-8 race rules apply to the delivery path unchanged).  Returns
        ``(query_id, unsubscribe)`` so the caller can watch the upstream's
        lifecycle, or None when no running query writes the source (the
        shared pipeline then owns a catchup consumer instead).

        ``batch_cb`` additionally subscribes at BATCH granularity:
        ``batch_cb(emits, raw_block)`` fires once per decoded emission
        batch before the per-emit fan-out; when the upstream runs on the
        device backend ``raw_block`` carries the emission batch's columnar
        arrays still device-resident (fused-residual handoff — the shared
        pipeline's tap kernel evaluates straight over them instead of
        bouncing through host rows)."""
        if not cfg._bool(self.config.get("ksql.query.push.v2.enabled", True)):
            return None
        for qid, h in list(self.queries.items()):
            if h.sink_name == source_name and h.is_running():
                h.push_listeners.append(cb)
                if batch_cb is not None:
                    h.push_batch_listeners.append(batch_cb)
                    self._arm_raw_emit_blocks(h)

                def unsubscribe(h=h, cb=cb, batch_cb=batch_cb):
                    try:
                        h.push_listeners.remove(cb)
                    except ValueError:
                        pass
                    if batch_cb is not None:
                        try:
                            h.push_batch_listeners.remove(batch_cb)
                        except ValueError:
                            pass
                        # last batch listener gone -> stop paying the
                        # per-batch device gather + block retention
                        self._arm_raw_emit_blocks(h)

                return qid, unsubscribe
        return None

    @staticmethod
    def _arm_raw_emit_blocks(handle: "QueryHandle") -> None:
        """Flip raw-block collection on the handle's CURRENT device
        executor (rebuilds re-arm via _build_executor) so the next decode
        keeps its columnar emit arrays for the batch listeners."""
        dev = getattr(handle.executor, "device", None)
        if dev is not None and getattr(
            handle.executor, "backend", ""
        ) == "device":
            dev.collect_raw_emits = bool(handle.push_batch_listeners)

    def register_push_listener(self, source_name: str, cb) -> Optional[Callable]:
        """ScalablePushRegistry analog (legacy single-session attach):
        like :meth:`register_push_tap` but returns only the unsubscribe
        callable, or None when no running query writes the source (caller
        falls back to a catchup consumer)."""
        attached = self.register_push_tap(source_name, cb)
        return attached[1] if attached is not None else None

    def get_push_registry(self):
        """Engine-side push-registry seam (tentpole): lazily construct the
        shared-pipeline registry that multiplexes compatible push sessions
        as filtered taps (server/push_registry.py).  Engine-owned so
        embedded callers, the REST server, metrics and shutdown all see
        the same instance."""
        if self.push_registry is None:
            from ksql_tpu.server.push_registry import PushRegistry

            self.push_registry = PushRegistry(self)
        return self.push_registry

    # ------------------------------------------------------------- sandbox
    #: statement types that mutate engine state and therefore validate on a
    #: sandbox fork first (SandboxedExecutionContext analog — the reference
    #: executes every distributed statement against a sandbox engine before
    #: enqueueing it, ksqldb-engine KsqlEngine.createSandbox)
    _MUTATING = ()

    def create_sandbox(self) -> "KsqlEngine":
        """Fork this engine for validation: copied metastore / schema
        registry / properties, a throwaway broker, no running queries.
        Executing a statement on the sandbox performs every check and
        planning step the real execution would, with all side effects
        landing on the fork."""
        sb_broker = Broker()
        for name in self.broker.list_topics():
            # mirror topic *metadata* (partition counts feed co-partitioning
            # checks) but none of the records — sandbox produces are dropped
            sb_broker.create_topic(name, self.broker.topic(name).num_partitions)
        sb = KsqlEngine(config=self.config, broker=sb_broker, registry=self.registry)
        sb.metastore = self.metastore.copy()
        sb.schema_registry = self.schema_registry.copy()
        sb.variables = dict(self.variables)
        sb.session_properties = dict(self.session_properties)
        sb.is_sandbox = True
        # validation must not pay an XLA compile per statement; the oracle
        # performs the identical plan/schema checks.  device-only is kept:
        # its lowering failure IS a validation error.
        if str(self.effective_property(cfg.RUNTIME_BACKEND, "device")).lower() in (
            "device", "distributed"
        ):
            sb.session_properties[cfg.RUNTIME_BACKEND] = "oracle"
        return sb

    # ------------------------------------------------------------ plumbing
    def effective_property(self, name: str, default=None):
        """Config value with session-property override (SET statement /
        request-scoped overrides take precedence, KsqlConfig semantics)."""
        if name in self.session_properties:
            return self.session_properties[name]
        return self.config.get(name, default)

    def _plog_append(self, where: str, message: str) -> None:
        """Host-side processing-log append with the shared retention cap
        (ksql.processing.log.buffer.size; exceeding it trims the oldest
        half and counts the drop)."""
        self.processing_log.append((where, message))
        if len(self.processing_log) > self._plog_cap:
            drop = max(self._plog_cap // 2, 1)
            del self.processing_log[:drop]
            self.plog_dropped += drop
        if getattr(self, "telemetry_enabled", False):
            try:
                self._timeline_annotate(where, message)
            except Exception:  # noqa: BLE001 — annotations never break
                pass  # the error path that produced the log entry

    def _timeline_annotate(self, where: str, message: str) -> None:
        """Route one processing-log entry onto timeline(s) as a lifecycle
        annotation.  Query-scoped categories (``rescale.done:<qid>``) land
        on that owner's timeline; engine-wide categories (overload
        engage/clear) stamp every LIVE timeline — a store is never created
        just to hold an annotation for an owner that has no series yet,
        except when the suffix names a known query (so cause is retained
        even for a query that has not ticked since startup)."""
        from ksql_tpu.common import timeline as tlm

        cat = tlm.plog_category(where)
        if cat not in tlm.ANNOTATION_CATEGORIES:
            return
        detail = message if ":" not in where else (
            where.split(":", 1)[1] + " — " + message
        )
        if cat in tlm.ENGINE_WIDE_CATEGORIES:
            for tl in list(self.timelines.values()) or [
                self.timeline_store("_engine")
            ]:
                tl.annotate(cat, detail)
            return
        target = where.split(":", 1)[1] if ":" in where else ""
        if target in self.timelines:
            self.timelines[target].annotate(cat, detail)
        elif target in self.queries:
            self.timeline_store(target).annotate(cat, detail)
        else:
            # no owner of that name: broadcast so the incident stays
            # observable ("_engine" backstops a pre-first-tick engine)
            for tl in list(self.timelines.values()) or [
                self.timeline_store("_engine")
            ]:
                tl.annotate(cat, detail)

    def _on_error(self, where: str, e: Exception) -> None:
        self._plog_append(where, f"{type(e).__name__}: {e}")
        if not self.is_sandbox:
            try:
                self._produce_processing_log(where, e)
            except Exception:  # noqa: BLE001 — the log must never recurse
                pass

    #: KSQL_PROCESSING_LOG record types (ProcessingLogMessageSchema)
    _PLOG_DESERIALIZATION_ERROR = 0
    _PLOG_RECORD_PROCESSING_ERROR = 2
    _plog_ready = False

    def _produce_processing_log(self, where: str, e: Exception) -> None:
        """Structured, queryable processing log (ProcessingLoggerImpl.java:23
        analog): every runtime error lands on the
        <service id>ksql_processing_log topic, and the KSQL_PROCESSING_LOG
        stream over it is auto-registered (ProcessingLogServerUtils)."""
        if not cfg._bool(self.config.get(cfg.PROCESSING_LOG_TOPIC_AUTO_CREATE)):
            return
        import json as _json
        import time as _time

        service_id = str(self.config.get(cfg.SERVICE_ID, "default_"))
        topic = f"{service_id}ksql_processing_log"
        if not self._plog_ready:
            self.broker.create_topic(topic)
            if self.metastore.get_source("KSQL_PROCESSING_LOG") is None:
                from ksql_tpu.common import types as T
                from ksql_tpu.common.types import SqlType

                b = LogicalSchema.builder()
                b.value_column("LOGGER", T.STRING)
                b.value_column("LEVEL", T.STRING)
                b.value_column("TIME", T.BIGINT)
                b.value_column(
                    "MESSAGE",
                    SqlType.struct(
                        [
                            ("TYPE", T.INTEGER),
                            ("ERRORMESSAGE", T.STRING),
                            ("CONTEXT", T.STRING),
                        ]
                    ),
                )
                self.metastore.put_source(
                    DataSource(
                        name="KSQL_PROCESSING_LOG",
                        source_type=DataSourceType.STREAM,
                        schema=b.build(),
                        topic=topic,
                        value_format="JSON",
                        sql_expression="-- auto-created processing log",
                    )
                )
            self._plog_ready = True
        mtype = (
            self._PLOG_DESERIALIZATION_ERROR
            if where.startswith("deserialize")
            else self._PLOG_RECORD_PROCESSING_ERROR
        )
        self.broker.topic(topic).produce(
            Record(
                key=None,
                value=_json.dumps(
                    {
                        "LOGGER": where,
                        "LEVEL": "ERROR",
                        "TIME": int(_time.time() * 1000),
                        "MESSAGE": {
                            "TYPE": mtype,
                            "ERRORMESSAGE": f"{type(e).__name__}: {e}",
                            "CONTEXT": where,
                        },
                    },
                    separators=(",", ":"),
                ),
                timestamp=int(_time.time() * 1000),
            )
        )

    def parse(self, sql: str) -> List[ast.PreparedStatement]:
        return parse_statements(
            sql, variables=self.variables, type_registry=self.metastore.all_types()
        )

    # --------------------------------------------------------------- entry
    def execute_sql(self, sql: str) -> List[StatementResult]:
        return [self.execute_statement(p) for p in self.parse(sql)]

    def execute_statement(self, prepared: ast.PreparedStatement) -> StatementResult:
        s = prepared.statement
        handler = self._HANDLERS.get(type(s))
        if handler is None:
            raise KsqlException(f"Unsupported statement: {type(s).__name__}")
        if (
            not self.is_sandbox
            and isinstance(s, self._MUTATING)
            and not prepared.__dict__.pop("_prevalidated", False)
        ):
            # validate on a fork first: a failing statement must leave the
            # metastore / schema registry / topics untouched
            self.create_sandbox().execute_statement(prepared)
        return handler(self, s, prepared.text)

    def validate_statement(self, prepared: ast.PreparedStatement) -> None:
        """Sandbox-only validation (SandboxedExecutionContext): raises on a
        bad statement without mutating engine state — a distributing server
        calls this BEFORE appending to the shared command log so user
        errors never poison peers' tail loops.  Marks the statement so the
        immediately-following execute does not sandbox a second time."""
        s = prepared.statement
        if isinstance(s, self._MUTATING):
            self.create_sandbox().execute_statement(prepared)
            prepared.__dict__["_prevalidated"] = True

    # ----------------------------------------------------------------- DDL
    @staticmethod
    def schema_from_elements(elements) -> LogicalSchema:
        b = LogicalSchema.builder()
        for el in elements:
            if el.constraint == ast.ColumnConstraint.KEY:
                b.key_column(el.name, el.type)
            elif el.constraint == ast.ColumnConstraint.PRIMARY_KEY:
                b.key_column(el.name, el.type)
            else:
                # HEADERS columns are value columns populated from record
                # headers, not the value payload (reference Column HEADERS
                # namespace)
                b.value_column(el.name, el.type)
        return b.build()

    @staticmethod
    def header_columns_of(elements):
        """[(column_name, header_key-or-None)] for HEADERS-backed columns,
        with type validation (HeadersColumnValidation analog)."""
        from ksql_tpu.common import types as T
        from ksql_tpu.common.types import SqlBaseType, SqlType

        out = []
        for el in elements:
            if el.constraint != ast.ColumnConstraint.HEADERS:
                continue
            if el.header_key is None:
                expected = SqlType.array(
                    SqlType.struct([("KEY", T.STRING), ("VALUE", T.BYTES)])
                )
                if el.type != expected:
                    raise KsqlException(
                        f"Invalid type for HEADERS column '{el.name}': "
                        "expected ARRAY<STRUCT<`KEY` STRING, `VALUE` BYTES>>, "
                        f"got {el.type}"
                    )
            else:
                if el.type.base != SqlBaseType.BYTES:
                    raise KsqlException(
                        f"Invalid type for HEADER('{el.header_key}') column "
                        f"'{el.name}': expected BYTES, got {el.type}"
                    )
            out.append((el.name, el.header_key))
        return tuple(out)

    def _prop(self, props: Dict[str, Any], name: str, default=None):
        for k, v in props.items():
            if k.upper() == name.upper():
                return v
        return default

    def _create_source(self, s, is_table: bool, text: str) -> StatementResult:
        props = s.properties
        existing = self.metastore.get_source(s.name)
        if existing is not None:
            if s.if_not_exists:
                return StatementResult("ddl", f"Source {s.name} already exists.")
            if not s.or_replace:
                raise KsqlException(
                    f"Cannot add {'table' if is_table else 'stream'} '{s.name}': "
                    "A source with the same name already exists"
                )
        if s.or_replace and (s.is_source or (existing is not None and existing.is_source)):
            kind_l = "table" if is_table else "stream"
            raise KsqlException(
                f"Cannot add {kind_l} '{s.name}': CREATE OR REPLACE is not "
                f"supported on source {kind_l}s."
            )
        topic_name = str(self._prop(props, "KAFKA_TOPIC", s.name))
        partitions = int(self._prop(props, "PARTITIONS", 1))
        from ksql_tpu.common.config import DEFAULT_KEY_FORMAT, DEFAULT_VALUE_FORMAT

        vf = self._prop(
            props, "VALUE_FORMAT",
            self._prop(props, "FORMAT",
                       self.effective_property(DEFAULT_VALUE_FORMAT) or None),
        )
        if vf is None:
            raise KsqlException(
                "Statement is missing the 'VALUE_FORMAT' property from the WITH "
                "clause. Either provide one or set a default via the "
                "'ksql.persistence.default.format.value' config."
            )
        value_format = str(vf).upper()
        key_format = str(self._prop(
            props, "KEY_FORMAT",
            self._prop(props, "FORMAT",
                       self.effective_property(DEFAULT_KEY_FORMAT) or "KAFKA"),
        )).upper()
        from ksql_tpu.serde import formats as _fmt

        if value_format not in _fmt.supported_formats():
            raise KsqlException(f"Unknown format: {value_format}")
        if key_format not in _fmt.supported_formats():
            raise KsqlException(f"Unknown format: {key_format}")
        if key_format == "NONE" and any(
            el.constraint in (ast.ColumnConstraint.KEY, ast.ColumnConstraint.PRIMARY_KEY)
            for el in s.elements
        ):
            raise KsqlException(
                "Key format specified as NONE for a source with key columns. "
                "The NONE format can only be used when no columns are defined."
            )
        from ksql_tpu.common.schema import PSEUDOCOLUMNS, WINDOW_BOUNDS

        for el in s.elements:
            if el.name in PSEUDOCOLUMNS or el.name in WINDOW_BOUNDS:
                raise KsqlException(
                    f"'{el.name}' is a reserved column name. You cannot use it "
                    "as a name for a column."
                )
            if is_table and el.constraint == ast.ColumnConstraint.KEY:
                raise KsqlException(
                    f"Column `{el.name}` is a 'KEY' column: please use "
                    "'PRIMARY KEY' for tables."
                )
            if not is_table and el.constraint == ast.ColumnConstraint.PRIMARY_KEY:
                raise KsqlException(
                    f"Column `{el.name}` is a 'PRIMARY KEY' column: please use "
                    "'KEY' for streams."
                )
        key_sid = self._prop(props, "KEY_SCHEMA_ID")
        value_sid = self._prop(props, "VALUE_SCHEMA_ID")
        from ksql_tpu.serde.schema_registry import SR_FORMATS

        if key_sid is not None:
            if key_format not in SR_FORMATS:
                raise KsqlException(
                    "KEY_FORMAT should support schema inference when "
                    f"KEY_SCHEMA_ID is provided. Current format is {key_format}."
                )
            if any(
                el.constraint in (ast.ColumnConstraint.KEY, ast.ColumnConstraint.PRIMARY_KEY)
                for el in s.elements
            ):
                raise KsqlException(
                    "Table elements and KEY_SCHEMA_ID cannot both exist for "
                    "create statement."
                )
        if value_sid is not None:
            if value_format not in SR_FORMATS:
                raise KsqlException(
                    "VALUE_FORMAT should support schema inference when "
                    f"VALUE_SCHEMA_ID is provided. Current format is {value_format}."
                )
            if any(
                el.constraint
                not in (ast.ColumnConstraint.KEY, ast.ColumnConstraint.PRIMARY_KEY,
                        ast.ColumnConstraint.HEADERS)
                for el in s.elements
            ):
                raise KsqlException(
                    "Table elements and VALUE_SCHEMA_ID cannot both exist for "
                    "create statement."
                )
        header_cols = self.header_columns_of(s.elements)
        schema = self.schema_from_elements(s.elements)
        schema = self._infer_schema(
            schema, topic_name, key_format, value_format, s.name,
            header_cols=header_cols,
            key_schema_id=int(key_sid) if key_sid is not None else None,
            value_schema_id=int(value_sid) if value_sid is not None else None,
            key_full_name=self._prop(props, "KEY_SCHEMA_FULL_NAME"),
            value_full_name=self._prop(props, "VALUE_SCHEMA_FULL_NAME"),
            value_unwrap=_parses_unwrapped(self._prop(props, "WRAP_SINGLE_VALUE")),
        )
        if is_table and not schema.key_columns:
            raise KsqlException(
                "Tables require a PRIMARY KEY. Please define the PRIMARY KEY."
            )
        if self._prop(props, "WINDOW_TYPE") and not schema.key_columns:
            raise KsqlException("Windowed sources require a key column.")
        for c in schema.key_columns:
            if _fmt.contains_map(c.type):
                raise KsqlException(
                    "Map keys, including types that contain maps, are not "
                    "supported as they may lead to unexpected behavior due to "
                    f"inconsistent serialization. Key column name: `{c.name}`. "
                    f"Column type: {c.type}"
                )
        _fmt.check_schema_support(value_format, schema.value_columns, "value")
        _fmt.check_schema_support(key_format, schema.key_columns, "key")
        wrap_raw = self._prop(props, "WRAP_SINGLE_VALUE")
        if wrap_raw is None and len(list(schema.value_columns)) == 1:
            # config default applies only when the user explicitly set it
            wrap_raw = self.session_properties.get(
                "ksql.persistence.wrap.single.values",
                self.config.explicit("ksql.persistence.wrap.single.values"),
            )
        wrap = _validate_wrap_property(wrap_raw, value_format, schema.value_columns)
        wt = self._prop(props, "WINDOW_TYPE")
        wsize = self._prop(props, "WINDOW_SIZE")
        if wt and str(wt).upper() == "SESSION" and wsize:
            raise KsqlException(
                "'WINDOW_SIZE' should not be set for SESSION windows."
            )
        window_size_ms = None
        if wsize:
            from ksql_tpu.parser.parser import Parser

            p = Parser(str(wsize))
            window_size_ms = p.parse_duration_ms()
        ts_col = self._prop(props, "TIMESTAMP")
        ts_fmt = self._prop(props, "TIMESTAMP_FORMAT")
        for pname, fmt_of in (
            ("VALUE_AVRO_SCHEMA_FULL_NAME", value_format),
            ("KEY_AVRO_SCHEMA_FULL_NAME", key_format),
            ("VALUE_SCHEMA_FULL_NAME", value_format),
            ("KEY_SCHEMA_FULL_NAME", key_format),
        ):
            fsn = self._prop(props, pname)
            if fsn is None:
                continue
            if not str(fsn).strip():
                raise KsqlException(
                    "fullSchemaName cannot be empty. Format configuration: "
                    "{fullSchemaName=}"
                )
            if "AVRO" in pname and fmt_of not in ("AVRO",):
                raise KsqlException(
                    f"{fmt_of} does not support the following configs: [fullSchemaName]"
                )
            if "AVRO" not in pname and fmt_of not in ("AVRO", "PROTOBUF", "JSON_SR"):
                raise KsqlException(
                    f"{fmt_of} does not support the following configs: [fullSchemaName]"
                )
        self.broker.create_topic(topic_name, partitions)
        self._register_subject_schemas(topic_name, key_format, value_format, schema)
        source = DataSource(
            name=s.name,
            source_type=DataSourceType.TABLE if is_table else DataSourceType.STREAM,
            schema=schema,
            topic=topic_name,
            key_format=KeyFormat(
                format=key_format,
                window_type=str(wt).upper() if wt else None,
                window_size_ms=window_size_ms,
                wrapped=getattr(self, "_inferred_wrapped_key", False),
            ),
            value_format=value_format,
            wrap_single_values=wrap,
            value_delimiter=(
                str(self._prop(props, "VALUE_DELIMITER"))
                if self._prop(props, "VALUE_DELIMITER") is not None
                else None
            ),
            key_delimiter=(
                str(self._prop(props, "KEY_DELIMITER"))
                if self._prop(props, "KEY_DELIMITER") is not None
                else None
            ),
            timestamp_column=str(ts_col).upper() if ts_col else None,
            timestamp_format=ts_fmt,
            sql_expression=text,
            is_source=s.is_source,
            header_columns=header_cols,
            proto_nullable_rep=(
                str(self._prop(props, "VALUE_PROTOBUF_NULLABLE_REPRESENTATION")).upper()
                if self._prop(props, "VALUE_PROTOBUF_NULLABLE_REPRESENTATION")
                else None
            ),
            proto_float32=getattr(self, "_inferred_proto_float32", ()),
        )
        self.metastore.put_source(source, allow_replace=s.or_replace or existing is not None)
        kind = "Table" if is_table else "Stream"
        return StatementResult("ddl", f"{kind} created")

    def _infer_schema(
        self, schema: LogicalSchema, topic: str, key_format: str, value_format: str,
        source_name: str, header_cols=(),
        key_schema_id=None, value_schema_id=None,
        key_full_name=None, value_full_name=None,
        value_unwrap: bool = False,
    ) -> LogicalSchema:
        """Schema inference from the registry (DefaultSchemaInjector analog):
        undeclared key/value columns come from the <topic>-key / <topic>-value
        subjects when the format is SR-backed; partial schemas (key declared,
        value inferred, or vice versa) are supported."""
        from ksql_tpu.serde.schema_registry import SR_FORMATS, columns_from_schema

        self._inferred_wrapped_key = False
        self._inferred_proto_float32 = ()
        header_names = {n for n, _ in header_cols}
        payload_value_columns = [
            c for c in schema.value_columns if c.name not in header_names
        ]
        need_key = not schema.key_columns and (
            key_format.upper() in SR_FORMATS or key_schema_id is not None
        )
        need_value = not payload_value_columns and (
            value_format.upper() in SR_FORMATS or value_schema_id is not None
        )
        if not (need_key or need_value):
            if not schema.key_columns and not schema.value_columns:
                raise KsqlException(
                    f"The statement does not define any columns and {source_name} "
                    "requires schema inference, which needs a schema registry "
                    "(not configured)."
                )
            return schema
        b = LogicalSchema.builder()
        if need_key:
            reg = (
                self.schema_registry.get_by_id(key_schema_id)
                if key_schema_id is not None
                else self.schema_registry.latest(f"{topic}-key")
            )
            if reg is not None and reg.schema_type == "PROTOBUF":
                # PROTOBUF does not support UNWRAP_SINGLES: the key message's
                # fields become the key columns and stay wrapped
                for name, t in columns_from_schema(
                    reg.schema_type, reg.schema, reg.references,
                    full_name=key_full_name,
                ):
                    b.key_column(name or "ROWKEY", t)
                    if name:
                        self._inferred_wrapped_key = True
            elif reg is not None:
                # key inference always yields ONE unwrapped column: the whole
                # physical schema (record keys become ROWKEY STRUCT<...>) —
                # DefaultSchemaInjector "key schema inference always results
                # in an unwrapped key" + SerdeUtils.wrapSingle(isKey=true)
                from ksql_tpu.serde.schema_registry import sql_type_from_schema

                t = sql_type_from_schema(
                    reg.schema_type, reg.schema, reg.references,
                    full_name=key_full_name,
                )
                b.key_column("ROWKEY", t)
        else:
            for c in schema.key_columns:
                b.key_column(c.name, c.type)
        inferred_value = False
        if need_value:
            reg = (
                self.schema_registry.get_by_id(value_schema_id)
                if value_schema_id is not None
                else self.schema_registry.latest(f"{topic}-value")
            )
            if reg is not None:
                inferred_value = True
                if value_unwrap:
                    # WRAP_SINGLE_VALUE=false: the whole schema is the single
                    # anonymous ROWVAL column (SerdeUtils.wrapSingle)
                    from ksql_tpu.serde.schema_registry import (
                        sql_type_from_schema,
                    )

                    b.value_column(
                        "ROWVAL",
                        sql_type_from_schema(
                            reg.schema_type, reg.schema, reg.references,
                            full_name=value_full_name,
                        ),
                    )
                else:
                    for name, t in columns_from_schema(
                        reg.schema_type, reg.schema, reg.references,
                        full_name=value_full_name,
                    ):
                        b.value_column(name or "ROWVAL", t)
                if reg.schema_type == "PROTOBUF":
                    from ksql_tpu.serde.schema_registry import protobuf_float_fields

                    self._inferred_proto_float32 = protobuf_float_fields(
                        reg.schema, reg.references, full_name=value_full_name
                    )
                # header-backed columns are not part of the payload schema;
                # they survive inference
                for c in schema.value_columns:
                    if c.name in header_names:
                        b.value_column(c.name, c.type)
        if not inferred_value:
            for c in schema.value_columns:
                b.value_column(c.name, c.type)
        out = b.build()
        if not out.key_columns and not out.value_columns:
            raise KsqlException(
                f"The statement does not define any columns and {source_name} "
                "requires schema inference, but no schema is registered for "
                f"topic {topic}."
            )
        return out

    def _h_create_stream(self, s: ast.CreateStream, text):
        return self._create_source(s, is_table=False, text=text)

    def _h_create_table(self, s: ast.CreateTable, text):
        return self._create_source(s, is_table=True, text=text)

    # ------------------------------------------------------- CSAS/CTAS/IAS
    def _persistent_query(self, s, query: ast.Query, is_table: bool, text: str,
                          sink_name: str, properties: Dict[str, Any],
                          insert_into: bool = False) -> StatementResult:
        existing = self.metastore.get_source(sink_name)
        if existing is not None and not insert_into:
            if getattr(s, "if_not_exists", False):
                return StatementResult("ddl", f"Source {sink_name} already exists.")
            if not getattr(s, "or_replace", False):
                raise KsqlException(
                    f"Cannot add {'table' if is_table else 'stream'} '{sink_name}': "
                    "A source with the same name already exists"
                )
        prefix = "INSERTQUERY" if insert_into else ("CTAS" if is_table else "CSAS")
        query_id = f"{prefix}_{sink_name}_{next(self._query_seq)}"
        analysis = analyze_query(query, self.metastore, self.registry, sink_name)
        self._validate_join_partitions(analysis)
        # explicit values only: several keys (e.g. wrap.single.values) change
        # behavior by mere presence; planner .get() calls supply defaults
        merged_config = dict(self.config._props)
        merged_config.update(self.session_properties)
        planned = self.planner.plan(
            analysis,
            query_id,
            sink_name=sink_name,
            sink_properties=properties,
            sink_is_table=is_table,
            config=merged_config,
        )
        planned = self._apply_schema_ids(planned, properties, sink_name)
        # verify BEFORE any registration side effect (sink source, topic,
        # SR subjects): a strict-mode rejection must leave no orphaned
        # metadata behind, exactly like the planner's own validations
        self._verify_plan_static(query_id, planned.plan)
        # memory admission rides the same pre-registration seam: an
        # over-budget strict rejection must also leave nothing behind
        mem_report = self._admit_memory_static(query_id, planned.plan)
        if planned.output_source is not None:
            self._register_subject_schemas(
                planned.output_source.topic,
                planned.output_source.key_format.format,
                planned.output_source.value_format,
                planned.output_source.schema,
            )
            # sink topics inherit a source topic's partition count unless
            # PARTITIONS is given; for joins the reference takes the RIGHT
            # side's count (JoinNode.getPartitions:196 returns
            # right.getPartitions), i.e. the rightmost source of the
            # left-deep join tree
            sink_topic = planned.output_source.topic
            if not self.broker.has_topic(sink_topic):
                p = properties.get("PARTITIONS") or properties.get("partitions")
                if p is not None:
                    n = int(p)
                else:
                    src_topic = analysis.sources[-1].source.topic
                    n = (
                        len(self.broker.topic(src_topic).partitions)
                        if self.broker.has_topic(src_topic)
                        else 1
                    )
                self.broker.create_topic(sink_topic, n)
        if insert_into:
            # target must exist and schemas must be compatible (implicit
            # numeric widening allowed, reference SchemaUtil.areCompatible)
            target = self.metastore.require_source(sink_name)
            if not _schemas_compatible(planned.output_source.schema, target.schema):
                raise PlanningException(
                    f"Incompatible schema between query and {sink_name}. "
                    f"Query schema: {planned.output_source.schema}. "
                    f"Target schema: {target.schema}."
                )
            planned = dataclasses.replace(planned, output_source=target)
        else:
            self.metastore.put_source(
                dataclasses.replace(planned.output_source, is_cas_target=True),
                allow_replace=getattr(s, "or_replace", False) or existing is not None,
            )
        self._start_query(query_id, planned, text, mem_report=mem_report)
        return StatementResult("query", f"Created query {query_id}", query_id=query_id)

    def _register_subject_schemas(self, topic, key_format, value_format, schema):
        """SR-backed formats register their subjects on creation (reference
        SchemaRegistryUtil): key first, then value, in statement order."""
        from ksql_tpu.serde.schema_registry import SR_FORMATS

        sr = self.schema_registry
        if str(key_format).upper() in SR_FORMATS and schema.key_columns:
            subj = f"{topic}-key"
            if not sr.has_subject(subj):
                sr.register(
                    subj, "KSQL", [(c.name, c.type) for c in schema.key_columns]
                )
        if str(value_format).upper() in SR_FORMATS and schema.value_columns:
            subj = f"{topic}-value"
            if not sr.has_subject(subj):
                sr.register(
                    subj, "KSQL", [(c.name, c.type) for c in schema.value_columns]
                )

    def _apply_schema_ids(self, planned: PlannedQuery, properties, sink_name):
        """KEY_SCHEMA_ID / VALUE_SCHEMA_ID on a CSAS/CTAS: the registered SR
        schema becomes the physical write schema.  The query's columns must be
        an in-order prefix of it (by name and type); schema columns beyond the
        query's are appended with their write-defaults (Avro field defaults,
        proto3 zero-values, JSON-schema null) — a required Avro field with no
        default is a serialization error (reference SchemaRegistryUtil)."""
        from ksql_tpu.serde.schema_registry import (
            NO_DEFAULT,
            columns_with_defaults,
        )
        from ksql_tpu.common.schema import LogicalSchema as _LS

        key_sid = self._prop(properties, "KEY_SCHEMA_ID")
        value_sid = self._prop(properties, "VALUE_SCHEMA_ID")
        if key_sid is None and value_sid is None:
            return planned
        sink = planned.plan.physical_plan
        schema = sink.schema
        new_formats = sink.formats
        value_defaults = []
        b = _LS.builder()

        def types_match(a, b):
            if a is None or b is None:
                return a is b
            if a.base != b.base:
                return False
            from ksql_tpu.common.types import SqlBaseType as _B

            if a.base == _B.STRUCT:
                af = [(n.upper(), t) for n, t in (a.fields or ())]
                bf = [(n.upper(), t) for n, t in (b.fields or ())]
                return len(af) == len(bf) and all(
                    an == bn and types_match(at, bt)
                    for (an, at), (bn, bt) in zip(af, bf)
                )
            if a.base in (_B.ARRAY, _B.MAP):
                return types_match(a.element, b.element)
            return True  # primitive params (decimal precision etc.) are lax

        def check_prefix(query_cols, sr_cols, what):
            mism = []
            for i, c in enumerate(query_cols):
                if (
                    i >= len(sr_cols)
                    or sr_cols[i][0].upper() != c.name.upper()
                    or not types_match(sr_cols[i][1], c.type)
                ):
                    mism.append(f"`{c.name}` {c.type}")
            if mism:
                sr_desc = ", ".join(f"`{n}` {t}" for n, t, _d in sr_cols)
                raise KsqlException(
                    f"The following {what} columns are changed, missing or "
                    f"reordered: [{', '.join(mism)}]. Schema from schema "
                    f"registry is [{sr_desc}]"
                )

        if key_sid is not None:
            reg = self.schema_registry.get_by_id(int(key_sid))
            if reg is None:
                raise KsqlException(f"Schema id {key_sid} not found.")
            if reg.schema_type == "PROTOBUF":
                # PROTOBUF keys stay wrapped: message fields are key columns
                sr_cols = columns_with_defaults(
                    reg.schema_type, reg.schema, reg.references
                )
                check_prefix(list(schema.key_columns), sr_cols, "key")
                for c in schema.key_columns:
                    b.key_column(c.name, c.type)
                new_formats = dataclasses.replace(new_formats, key_wrapped=True)
            else:
                # keys are always unwrapped: the SR schema is the single key
                # column's type (SerdeUtils.wrapSingle(isKey=true)); the
                # synthesized column keeps the query's key name
                from ksql_tpu.serde.schema_registry import (
                    NO_DEFAULT as _ND,
                    sql_type_from_schema,
                )

                kt = sql_type_from_schema(
                    reg.schema_type, reg.schema, reg.references
                )
                kcols = list(schema.key_columns)
                sr_kcols = [(kcols[0].name if kcols else "ROWKEY", kt, _ND)]
                check_prefix(kcols, sr_kcols, "key")
                for c in schema.key_columns:
                    b.key_column(c.name, c.type)
                new_formats = dataclasses.replace(
                    new_formats, key_wrapped=False
                )
        else:
            for c in schema.key_columns:
                b.key_column(c.name, c.type)
        if value_sid is not None:
            reg = self.schema_registry.get_by_id(int(value_sid))
            if reg is None:
                raise KsqlException(f"Schema id {value_sid} not found.")
            sr_cols = columns_with_defaults(reg.schema_type, reg.schema, reg.references)
            qcols = list(schema.value_columns)
            check_prefix(qcols, sr_cols, "value")
            if reg.schema_type == "AVRO" and isinstance(reg.schema, dict):
                # nested non-optional fields with schema defaults: a null
                # written there takes the default (Connect AvroData rules);
                # recorded as (path-tuple, default) entries
                sr_fields = list(reg.schema.get("fields", ()))
                for i, c in enumerate(qcols):
                    if i < len(sr_fields):
                        value_defaults.extend(
                            _avro_nested_defaults((c.name,), sr_fields[i]["type"])
                        )
            for i, (n, t, d) in enumerate(sr_cols):
                if i < len(qcols):
                    b.value_column(qcols[i].name, qcols[i].type)
                    continue
                b.value_column(n, t)
                if d is NO_DEFAULT:
                    raise KsqlException(
                        f"Error serializing message to topic: {sink.topic}. "
                        f"Missing default value for required Avro field: "
                        f"[{n.lower()}]. This field appears in Avro schema "
                        "in Schema Registry"
                    )
                value_defaults.append((n, d))
        else:
            for c in schema.value_columns:
                b.value_column(c.name, c.type)
        new_schema = b.build()
        new_sink = dataclasses.replace(
            sink,
            schema=new_schema,
            formats=new_formats,
            value_defaults=tuple(value_defaults),
        )
        new_plan = dataclasses.replace(planned.plan, physical_plan=new_sink)
        out_src = planned.output_source
        if out_src is not None:
            out_src = dataclasses.replace(
                out_src,
                schema=new_schema,
                key_format=dataclasses.replace(
                    out_src.key_format, wrapped=new_formats.key_wrapped
                ),
            )
        return dataclasses.replace(planned, plan=new_plan, output_source=out_src)

    def _validate_join_partitions(self, analysis) -> None:
        """Co-partitioning requirement: joined sources' topics must have the
        same partition count (reference JoinNode.validatePartitionCounts)."""
        from ksql_tpu.analyzer.analyzer import JoinInfo, _is_fk_join

        if not isinstance(analysis.relation, JoinInfo) or len(analysis.sources) < 2:
            return
        if _is_fk_join(analysis.relation):
            return  # FK joins do not require co-partitioning (reference JoinNode)
        counts = []
        for asrc in analysis.sources:
            if not self.broker.has_topic(asrc.source.topic):
                continue  # unknown count: skip just this source
            counts.append(
                (asrc.source.name, len(self.broker.topic(asrc.source.topic).partitions))
            )
        if not counts:
            return
        first_name, first_n = counts[0]
        for name, n in counts[1:]:
            if n != first_n:
                raise PlanningException(
                    f"Can't join `{first_name}` with `{name}` since the number "
                    f"of partitions don't match. `{first_name}` partitions = "
                    f"{first_n}; `{name}` partitions = {n}. Please repartition "
                    "either one so that the number of partitions match."
                )

    def _verify_plan_static(self, query_id: str, plan) -> None:
        """Static plan verification (ksql.analysis.verify.plans, default
        on): walk the ExecutionStep DAG before any executor exists and
        check the invariants every backend assumes — schema propagation,
        key consistency across repartitions, window/serde sanity.  The
        reference validates the serialized plan the same way before
        building the Streams topology; violations here log to the
        processing log (or reject the statement under
        ksql.analysis.verify.strict)."""
        if not cfg._bool(
            self.effective_property(cfg.ANALYSIS_VERIFY_PLANS, True)
        ):
            return
        from ksql_tpu.analysis import verify_plan

        violations = verify_plan(plan)
        if not violations:
            return
        detail = "; ".join(v.format() for v in violations)
        if cfg._bool(self.effective_property(cfg.ANALYSIS_VERIFY_STRICT)):
            raise KsqlException(
                f"plan failed static verification ({len(violations)} "
                f"violation(s)): {detail}"
            )
        self._plog_append(
            f"plan.verify:{query_id}",
            f"{len(violations)} static plan violation(s): {detail}",
        )

    # ------------------------------------------- static memory model (graftmem)
    def _memory_shards(self) -> int:
        """Mesh size the memory model prices a new plan at: the configured
        ksql.device.shards under backend=distributed (0 = all visible
        devices), 1 otherwise."""
        backend = str(self.effective_property(cfg.RUNTIME_BACKEND)).lower()
        if backend != "distributed":
            return 1
        n = int(self.effective_property(cfg.DEVICE_SHARDS, 0) or 0)
        if n:
            return n
        import jax as _jax

        return max(1, len(_jax.devices()))

    def _memory_report_static(self, plan):
        """Static device-memory footprint (analysis/mem_model) of a plan
        under the engine's effective lowering parameters, or None when it
        does not lower to the device backend — oracle plans hold no
        modeled HBM."""
        from ksql_tpu.analysis import analyze_plan_memory
        from ksql_tpu.runtime.device_executor import (
            _is_suppress,
            _needs_per_record,
        )

        if str(
            self.effective_property(cfg.RUNTIME_BACKEND)
        ).lower() == "oracle":
            return None  # the row oracle allocates no device memory
        self._install_function_limits()
        sliced_opt = (
            None
            if cfg._bool(self.effective_property(cfg.SLICING_ENABLE, True))
            else False
        )
        budget = int(
            self.effective_property(cfg.MEMORY_BUDGET_BYTES, 0) or 0
        )
        # mirror the runtime's effective batch capacity exactly, as the
        # backend classifier does: per-record cadence (configured or
        # plan-forced) constructs the device at capacity 1 (suppress
        # excepted), which sizes ss buffers and the transient
        # pipeline/exchange components
        per_record = (
            cfg._bool(self.effective_property(cfg.EMIT_CHANGES_PER_RECORD))
            or cfg._bool(self.effective_property(cfg.PARITY_MODE))
            or _needs_per_record(plan)
        )
        capacity = (
            1 if (per_record and not _is_suppress(plan))
            else int(self.config.get(cfg.BATCH_CAPACITY))
        )
        try:
            return analyze_plan_memory(
                plan, self.registry,
                capacity=capacity,
                store_capacity=int(self.config.get(cfg.STATE_SLOTS)),
                n_shards=self._memory_shards(),
                sliced=sliced_opt,
                slice_ring_max=int(
                    self.effective_property(cfg.SLICING_MAX_RING, 512)
                ),
                growth_budget_bytes=budget or None,
            )
        except Exception:  # noqa: BLE001 — DeviceUnsupported and any
            # probe-construction failure alike: the plan runs off-device,
            # where this model has nothing to say
            return None

    def _admit_memory_static(self, query_id: str, plan):
        """Memory admission gate (``ksql.analysis.memory.budget.bytes``):
        price the plan's per-shard at-creation footprint with the static
        model BEFORE any registration side effect.  Over budget: log a
        ``memory.admit`` plog entry naming the dominant components, or
        reject the statement under ``ksql.analysis.memory.budget.strict``
        (same contract as plan verification's strict mode).  Returns the
        report for the handle's EXPLAIN/gauge memo."""
        from ksql_tpu.analysis.mem_model import POINT_CREATION

        report = self._memory_report_static(plan)
        budget = int(
            self.effective_property(cfg.MEMORY_BUDGET_BYTES, 0) or 0
        )
        if report is None or not budget:
            return report
        need = report.per_shard_bytes(POINT_CREATION)
        shared_note = ""
        marginal = self._mqo_admission_marginal(plan, report)
        if marginal is not None:
            # the plan will ride a shared pipeline: the gate charges the
            # attach what it actually allocates — the shared ring's
            # marginal growth at the post-gcd width — not the phantom
            # standalone store the full report prices
            need, shared_note = marginal
        if need <= budget:
            return report
        if shared_note:
            # the rejected price is the shared ring's marginal growth —
            # the standalone report's components are the pipeline this
            # query will NOT build; steer at the levers that shrink the
            # marginal attach instead
            msg = (
                f"estimated per-shard device footprint {need} bytes"
                f"{shared_note} exceeds "
                f"{cfg.MEMORY_BUDGET_BYTES}={budget} — shrink the shared "
                "slice ring (an explicit GRACE PERIOD lowers retention, "
                f"{cfg.SLICING_MAX_RING} caps it) or raise the budget"
            )
        else:
            top = sorted(
                (c for c in report.components if c.at_creation),
                key=lambda c: -c.at_creation,
            )[:3]
            doms = ", ".join(
                f"{c.name}={c.at_creation}B"
                + (f" (cap {c.capacity})" if c.capacity else "")
                for c in top
            )
            msg = (
                f"estimated per-shard device footprint {need} bytes "
                f"exceeds "
                f"{cfg.MEMORY_BUDGET_BYTES}={budget}; dominant component(s): "
                f"{doms} — lower ksql.state.slots / ksql.batch.capacity or "
                "raise the budget"
            )
        if cfg._bool(self.effective_property(cfg.MEMORY_BUDGET_STRICT)):
            raise KsqlException(
                f"statement rejected by the memory admission gate: {msg}"
            )
        self._plog_append(f"memory.admit:{query_id}", msg)
        return report

    def _mqo_admission_marginal(self, plan, report):
        """When ``plan`` would attach to a running shared window family,
        return ``(marginal_bytes, note)`` — the attach's MARGINAL
        footprint (mem_model.family_attach_marginal: the shared ring
        re-priced at the post-gcd width with the union partial set) for
        the admission gate — else None (standalone pricing applies)."""
        if not self._mqo_enabled() or not self.window_families:
            return None
        if not cfg._bool(
            self.effective_property(cfg.SLICING_SHARE_FAMILIES, True)
        ):
            # build time runs the normal ladder when family sharing is
            # off — the gate must price the standalone store the query
            # will actually allocate, not a phantom attach
            return None
        from ksql_tpu.planner import mqo
        from ksql_tpu.runtime.lowering import CompiledDeviceQuery

        try:
            sliced_opt = (
                None
                if cfg._bool(self.effective_property(cfg.SLICING_ENABLE, True))
                else False
            )
            probe = CompiledDeviceQuery(
                plan, self.registry, capacity=1, analyze_only=True,
                sliced=sliced_opt,
                slice_ring_max=int(
                    self.effective_property(cfg.SLICING_MAX_RING, 512)
                ),
            )
            prim_qid, pex = self._find_family_primary(probe)
            if prim_qid is None:
                return None
            decision = mqo.decide_family_attach(
                pex.device, probe, primary_qid=prim_qid,
                max_members=int(
                    self.effective_property(cfg.MQO_MAX_MEMBERS, 32)
                ),
                standalone_bytes=report.per_shard_bytes(),
                budget_bytes=int(
                    self.effective_property(cfg.MEMORY_BUDGET_BYTES, 0) or 0
                ),
            )
            if not decision.share:
                return None
            return decision.marginal_bytes, (
                f" (marginal: shared window-family attach to {prim_qid} "
                f"at gcd width {decision.gcd_width_ms}ms)"
            )
        except Exception as e:  # noqa: BLE001 — the admission probe must
            # never block a statement: standalone pricing applies.  But a
            # broken cost model silently un-pricing every shared attach is
            # invisible otherwise — keep the signal.
            self._on_error("mqo-admission", e)
            return None

    def _classify_plan_static(self, plan, handle: Optional[QueryHandle] = None):
        """Ahead-of-time backend placement for EXPLAIN: replay the
        _build_executor fallback ladder without building an executor
        (no broker wiring, no state allocation, no XLA compile).  Running
        queries memoize the decision on their handle — the plan is
        immutable, so the deep probe runs once per effective config."""
        from ksql_tpu.analysis import classify_plan

        import re as _re

        backend = str(self.effective_property(cfg.RUNTIME_BACKEND)).lower()
        per_record = (
            cfg._bool(self.effective_property(cfg.EMIT_CHANGES_PER_RECORD))
            or cfg._bool(self.effective_property(cfg.PARITY_MODE))
        )
        capacity = int(self.config.get(cfg.BATCH_CAPACITY))
        store_capacity = int(self.config.get(cfg.STATE_SLOTS))
        # the memo key must cover EVERY classification input, or a SET /
        # ALTER SYSTEM between EXPLAINs serves a stale decision: backend,
        # cadence, the device capacities, and the function limits the
        # deep probe bakes into collect/topk state sizes
        limits = tuple(sorted(
            (str(k), str(v))
            for k, v in {**self.config.to_dict(),
                         **self.session_properties}.items()
            if _re.fullmatch(r"ksql\.functions\.\w+\.limit", str(k))
        ))
        sliced_opt = (
            None
            if cfg._bool(self.effective_property(cfg.SLICING_ENABLE, True))
            else False
        )
        ring_max = int(self.effective_property(cfg.SLICING_MAX_RING, 512))
        key = (backend, per_record, capacity, store_capacity, limits,
               sliced_opt, ring_max)
        if handle is not None and handle.static_decision is not None:
            cached_key, decision = handle.static_decision
            if cached_key == key:
                return decision
        self._install_function_limits()
        decision = classify_plan(
            plan, self.registry, backend=backend, per_record=per_record,
            capacity=capacity,
            store_capacity=store_capacity,
            deep=True,
            sliced=sliced_opt, slice_ring_max=ring_max,
        )
        if handle is not None:
            handle.static_decision = (key, decision)
        return decision

    def _wrap_transient_plan(self, plan, query_id: str):
        """The transient device path's plan prep, shared with its static
        classifier so EXPLAIN cannot drift from what stream_query builds:
        sinkless plans get a throwaway sink as the device emission
        boundary, serde semantics are annotated, function limits
        installed."""
        pp = plan.physical_plan
        if not isinstance(pp, (st.StreamSink, st.TableSink)):
            pp = st.StreamSink(
                source=pp,
                topic=f"__transient_{query_id}",
                formats=st.FormatInfo(),
                schema=pp.schema,
            )
        tplan = dataclasses.replace(plan, physical_plan=pp)
        self.annotate_serde_semantics(tplan)
        # collect/topk device state sizes from the configured caps
        self._install_function_limits()
        return tplan

    def _classify_transient_static(self, plan):
        """Ahead-of-time placement for EXPLAIN <query>: a sinkless plan
        describes the TRANSIENT path, which wraps it in a synthetic sink,
        runs per-record, and only probes the single-device rung (never
        distributed; device-only still degrades to the oracle there) —
        classifying the raw plan would report "oracle: plan without sink"
        for a query that actually runs on device."""
        from ksql_tpu.analysis import classify_plan

        if isinstance(plan.physical_plan, (st.StreamSink, st.TableSink)):
            return self._classify_plan_static(plan)
        backend = str(self.effective_property(cfg.RUNTIME_BACKEND)).lower()
        tplan = self._wrap_transient_plan(plan, "explain")
        return classify_plan(
            tplan, self.registry,
            backend="oracle" if backend == "oracle" else "device",
            per_record=True,
            capacity=int(self.config.get(cfg.BATCH_CAPACITY)),
            store_capacity=int(self.config.get(cfg.STATE_SLOTS)),
            deep=True,
        )

    def _h_csas(self, s: ast.CreateStreamAsSelect, text):
        return self._persistent_query(s, s.query, False, text, s.name, s.properties)

    def _h_ctas(self, s: ast.CreateTableAsSelect, text):
        return self._persistent_query(s, s.query, True, text, s.name, s.properties)

    def _h_insert_into(self, s: ast.InsertInto, text):
        target = self.metastore.require_source(s.target)
        if target.is_table():
            raise KsqlException("INSERT INTO can only be used to insert into a stream.")
        if target.is_source:
            raise KsqlException(
                f"Cannot insert into read-only stream: {s.target}"
            )
        if target.header_columns:
            raise KsqlException(
                f"Cannot insert into {s.target}: inserting into a stream with "
                "HEADER columns is not supported"
            )
        props = {
            "KAFKA_TOPIC": target.topic,
            "VALUE_FORMAT": target.value_format,
            "KEY_FORMAT": target.key_format.format,
            # synthesized from the target, not user-specified: exempt from
            # the keyless-sink KEY_FORMAT validation
            "__KEY_FORMAT_IMPLICIT__": True,
        }
        return self._persistent_query(
            s, s.query, False, text, s.target, props, insert_into=True
        )

    def _build_executor(self, handle: QueryHandle, live=None):
        """Construct the query's executor over the backend seam (device
        with oracle fallback) — used at start and by self-healing restarts.

        ``live`` is the rebuild fence (a zero-arg callable) when the call
        runs on a supervised rebuild worker: a worker abandoned at the
        rebuild deadline keeps executing this function as a zombie, so
        every mutation of shared handle/engine state below (emit-fence
        swap, backend gauges, family registration, member detach) is
        guarded — the zombie builds a muted, unregistered executor its
        caller then discards."""
        from ksql_tpu.functions.udafs import _hashable

        if live is None:
            def live() -> bool:
                return True

        query_id = handle.query_id
        plan = handle.plan
        qmetrics = self.metrics.for_query(query_id)

        # one fence per executor build: revoking the PREVIOUS build's fence
        # here makes "replaced executor" imply "silenced emit path" even
        # when the replaced executor's thread is a live zombie
        fence = {"live": True}
        if live():
            if handle.emit_fence is not None:
                handle.emit_fence["live"] = False
            handle.emit_fence = fence
        else:
            # fenced-off rebuild zombie: its executor is born muted and
            # must not revoke the fence a later successful build installed
            fence["live"] = False

        def on_emit(e: SinkEmit):
            if not fence["live"]:
                return  # fenced-off zombie executor: drop the stale emit
            k = (_hashable(e.key), e.window)
            handle.materialized[k] = (e.row, e.window, e.key, e.ts)
            qmetrics.messages_out.mark(1)
            if handle.progress is not None:
                # e2e latency = produce wall-time − record timestamp; the
                # emit's ts carries the record's event time on every
                # backend (device micro-batches may approximate a batch's
                # emissions with their batched decode timestamps)
                handle.progress.record_e2e(e.ts)
                # freshness clock for the materialized shadow — the gauge
                # standby replicas (sink disabled, no e2e samples) gossip
                handle.progress.note_materialized()
            for cb in list(handle.push_listeners):
                try:
                    cb(e)
                except Exception as exc:  # noqa: BLE001 — a slow/broken
                    self._on_error("scalable-push", exc)  # subscriber must
                    # not take down the persistent query

        def on_query_error(where: str, exc: Exception) -> None:
            qmetrics.errors.mark(1)
            self._on_error(where, exc)

        def note_backend(new: str) -> None:
            """Move the query between the backend-resident gauges — restarts
            can demote distributed→device→oracle (or re-promote), and a
            query must only ever count under the backend it runs on."""
            if not live():
                return  # fenced-off rebuild: gauges track the real build
            old = handle.backend
            if old == new:
                return
            if old == "device":
                self.device_query_count -= 1
            elif old == "distributed":
                self.distributed_query_count -= 1
            if new == "device":
                self.device_query_count += 1
            elif new == "distributed":
                self.distributed_query_count += 1
            handle.backend = new

        backend = str(self.effective_property(cfg.RUNTIME_BACKEND)).lower()
        if backend not in ("device", "oracle", "device-only", "distributed"):
            raise KsqlException(f"unknown {cfg.RUNTIME_BACKEND}: {backend}")
        # collect/topk device state is sized from the configured caps at
        # construction time — make the overrides visible before lowering
        self._install_function_limits()
        per_record = (
            cfg._bool(self.effective_property(cfg.EMIT_CHANGES_PER_RECORD))
            or cfg._bool(self.effective_property(cfg.PARITY_MODE))
        )
        sliced_opt = (
            None
            if cfg._bool(self.effective_property(cfg.SLICING_ENABLE, True))
            else False
        )
        ring_max = int(self.effective_property(cfg.SLICING_MAX_RING, 512))
        # a rebuild of a CURRENT family member must first detach its spec
        # from the primary's pipeline: if the ladder below ends standalone
        # (sharing disabled, signature drift, primary paused), a stale
        # member spec would keep producing to this query's sink alongside
        # the new executor — every member row emitted twice
        if live():
            self._detach_member_of(handle.query_id)
        executor = None
        if backend != "oracle" and not per_record and live():
            # multi-query optimizer: a sliced hopping plan correlated with
            # a running sliced pipeline attaches to it instead of building
            # its own consumer + device store, and a compatible stateless
            # chain rides a shared source-prefix pipeline (per-record
            # cadence keeps a standalone executor — member emission is
            # batch-coalesced)
            executor = self._try_attach_family(
                handle, on_emit, on_query_error, sliced_opt, ring_max
            )
            if executor is None:
                executor = self._try_attach_prefix(
                    handle, on_emit, on_query_error
                )
            if executor is not None:
                note_backend("device")
        if executor is None and backend == "distributed":
            # rung 1 of the fallback ladder: the full device mesh.  A
            # DeviceUnsupported here is a DISTRIBUTION gap (EMIT FINAL,
            # n-way join chains, per-record cadence, ...) — the plan may
            # still lower single-device, so fall through to rung 2 below
            # rather than straight to the oracle.
            from ksql_tpu.compiler.jax_expr import DeviceUnsupported
            from ksql_tpu.runtime.device_executor import (
                DistributedDeviceExecutor,
            )

            try:
                executor = DistributedDeviceExecutor(
                    plan, self.broker, self.registry,
                    on_error=on_query_error, emit_callback=on_emit,
                    batch_size=int(self.config.get(cfg.BATCH_CAPACITY)),
                    per_record=per_record,
                    store_capacity=int(self.config.get(cfg.STATE_SLOTS)),
                    # the live-rescale controller overrides the configured
                    # mesh size per query; a plain restart keeps whatever
                    # size the query last ran at
                    n_shards=int(
                        handle.shard_override
                        or self.effective_property(cfg.DEVICE_SHARDS, 0)
                        or 0
                    ) or None,
                    sliced=sliced_opt, slice_ring_max=ring_max,
                )
                note_backend("distributed")
                if live() and getattr(
                    executor, "native_ingest_bypassed", False
                ):
                    # the mesh-aware lane split keeps the C++ tier engaged
                    # for every eligible plan, so this counter should stay
                    # at zero — it remains armed so any future executor
                    # regression that reintroduces the bypass is counted
                    # (and tested) instead of silently degrading
                    reason = NATIVE_INGEST_BYPASS_REASON
                    self.fallback_reasons[reason] = (
                        self.fallback_reasons.get(reason, 0) + 1
                    )
            except DeviceUnsupported as e:
                if live():  # a fenced-off rebuild's discarded build must
                    # not count (nor lose-update) the live counters
                    self.fallback_reasons[str(e)] = (
                        self.fallback_reasons.get(str(e), 0) + 1
                    )
            except Exception as e:  # noqa: BLE001 — mesh/compile failures
                # degrade to single-device rather than abort the statement
                self._on_error("distributed-lowering", e)
        if executor is None and backend != "oracle":
            from ksql_tpu.compiler.jax_expr import DeviceUnsupported
            from ksql_tpu.runtime.device_executor import DeviceExecutor

            try:
                executor = DeviceExecutor(
                    plan, self.broker, self.registry,
                    on_error=on_query_error, emit_callback=on_emit,
                    batch_size=int(self.config.get(cfg.BATCH_CAPACITY)),
                    # batched by default; per-record changelog cadence when
                    # explicitly requested or under golden-file parity mode
                    per_record=per_record,
                    store_capacity=int(self.config.get(cfg.STATE_SLOTS)),
                    sliced=sliced_opt, slice_ring_max=ring_max,
                )
                note_backend("device")
            except DeviceUnsupported as e:
                if backend == "device-only":
                    raise KsqlException(
                        f"plan does not lower to the device backend: {e}"
                    ) from e
                if live():
                    self.fallback_reasons[str(e)] = (
                        self.fallback_reasons.get(str(e), 0) + 1
                    )
            except Exception as e:  # noqa: BLE001 — any construction failure
                # (XLA compile error, layout bug, OOM sizing) must not abort
                # the statement when the oracle can still run it; surface it
                # through the processing log and fall back
                if backend == "device-only":
                    raise
                self._on_error("device-lowering", e)
        if executor is None:
            executor = OracleExecutor(
                plan, self.broker, self.registry,
                on_error=on_query_error, emit_callback=on_emit,
            )
            note_backend("oracle")
        dev = getattr(executor, "device", None)
        if dev is not None:
            # HBM budget enforcement at _grow time (graftmem follow-up):
            # the at-growth-cap price is advisory at admission; the gate
            # here BLOCKS a store doubling that would overflow the budget,
            # logging memory.grow.refuse once per refused capacity.  Set
            # on the wrapped compiled query for the distributed runner
            # (which does not grow online, but keeps the seam uniform).
            compiled_dev = getattr(dev, "c", dev)
            compiled_dev.memory_budget_bytes = int(
                self.effective_property(cfg.MEMORY_BUDGET_BYTES, 0) or 0
            )

            def on_grow_refuse(msg, component, projected, budget,
                               _qid=query_id):
                if not fence["live"]:
                    return  # a zombie's store cannot refuse for the live one
                self._plog_append(f"memory.grow.refuse:{_qid}", msg)
                if handle.progress is not None:
                    handle.progress.note_event(
                        "memory.grow.refuse", component=component,
                        projectedBytes=int(projected),
                        budgetBytes=int(budget),
                    )

            compiled_dev.on_grow_refuse = on_grow_refuse
            # a hopping query that lowered but kept the k-fold expansion
            # path is a windowing-SHAPE fallback inside the device backend:
            # count its DeviceUnsupported-style reason so the silently
            # k-fold-expanded query is visible in /metrics
            wf = getattr(dev, "windowing_fallback", None)
            if wf and live():
                self.fallback_reasons[wf] = (
                    self.fallback_reasons.get(wf, 0) + 1
                )
            if live():
                self._register_family(handle, executor)
            dec = getattr(handle, "mqo_decision", None)
            if live() and dec is not None and dec.share:
                # admitted at its shared-attach MARGINAL price but built
                # STANDALONE after all (attach refusal, primary gone,
                # promotion): the full standalone footprint materializes
                # now — re-check the budget LOUDLY.  Never fatal: killing
                # a query at failover is worse than over-budget evidence.
                budget = int(
                    self.effective_property(cfg.MEMORY_BUDGET_BYTES, 0) or 0
                )
                mem = handle.mem_report
                if budget and mem is not None:
                    need = mem.per_shard_bytes()
                    if need > budget:
                        msg = (
                            f"standalone build of {handle.query_id} "
                            f"materializes its full footprint {need} "
                            f"bytes past {cfg.MEMORY_BUDGET_BYTES}="
                            f"{budget} (admission priced the shared-"
                            "attach marginal; the shared pipeline is "
                            "gone or refused the attach)"
                        )
                        self._plog_append(
                            f"memory.admit:{handle.query_id}", msg
                        )
                        if handle.progress is not None:
                            handle.progress.note_event(
                                "memory.admit", projectedBytes=int(need),
                                budgetBytes=budget,
                            )
        from ksql_tpu.runtime.device_executor import FamilyMemberExecutor

        if dev is not None or isinstance(executor, FamilyMemberExecutor):
            # micro-batched backends get bounded per-emit produce retries:
            # replaying a whole micro-batch over one transient sink fault
            # is the expensive alternative (a failed produce raises before
            # the record enters the log, so retrying cannot duplicate)
            executor.sink_writer.produce_retries = int(
                self.effective_property(cfg.SINK_PRODUCE_RETRIES, 2)
            )
        executor.sink_writer.enabled = not handle.standby
        if self._changelog_for(handle) is not None:
            # arm the durable-emission capture BEFORE the first tick: the
            # changelog frame journals each tick's sink records alongside
            # the state delta (runtime/changelog.py)
            executor.sink_writer.journal_buf = []
        if dev is not None and getattr(executor, "backend", "") == "device":
            # batch-level push fan-out (fused tap residuals): one call per
            # decoded emission batch, carrying the still-device-resident
            # columnar emit block when collection is armed.  Fence-guarded
            # like on_emit — a zombie's batches never reach the taps.
            def on_emit_batch(emits, _dev=dev):
                if not fence["live"] or not handle.push_batch_listeners:
                    return
                blk = getattr(_dev, "last_raw_block", None)
                if blk is not None and (
                    blk.get("n") != len(emits)
                    or blk.get("emits_id") != id(emits)
                ):
                    blk = None  # misaligned (other decode): host path
                for bcb in list(handle.push_batch_listeners):
                    try:
                        bcb(emits, blk)
                    except Exception as exc:  # noqa: BLE001 — a broken
                        self._on_error("scalable-push-batch", exc)  # tap
                        # must not take down the persistent query

            executor.batch_emit_callback = on_emit_batch
            dev.collect_raw_emits = bool(handle.push_batch_listeners)
        return executor

    def _mqo_enabled(self) -> bool:
        return cfg._bool(self.effective_property(cfg.MQO_ENABLE, True))

    def _mqo_count(self, decision) -> None:
        """Cost-model verdict counters (ksql_mqo_decisions_total{verdict};
        rejects additionally count as attach refusals so cost-model
        rejects and runtime refusals aggregate in one series)."""
        v = decision.verdict
        self.mqo_decisions[v] = self.mqo_decisions.get(v, 0) + 1
        if not decision.share:
            code = decision.reason_code
            self.family_attach_refused[code] = (
                self.family_attach_refused.get(code, 0) + 1
            )

    #: refusal codes that are RUNTIME-refusal-class (the slice store's
    #: live contents or the ring cap force a standalone build) — loud:
    #: family.reslice.refuse plog + /alerts evidence, whether the cost
    #: model pre-empted them or lowering raised FamilyAttachRefused
    _FAMILY_REFUSAL_CODES = ("reslice", "new-partials", "ring-cap")

    def _family_refusal_evidence(self, handle, prim_qid, reason_code, msg,
                                 details=None) -> None:
        """Classified attach-refusal evidence: family.reslice.refuse plog
        + /alerts evidence naming the primary and the structured details
        (old->new width, store size)."""
        self._plog_append(f"family.reslice.refuse:{handle.query_id}", msg)
        if handle.progress is not None:
            handle.progress.note_event(
                "family.reslice.refuse", reason=reason_code,
                primary=prim_qid, message=msg,
                **{k: v for k, v in (details or {}).items()},
            )

    def _note_family_refusal(self, handle, prim_qid, reason_code, msg,
                             details=None) -> None:
        """A RUNTIME attach refusal (lowering.FamilyAttachRefused): count
        it under the {reason} series the cost-model rejects share, and
        surface the classified evidence."""
        self.family_attach_refused[reason_code] = (
            self.family_attach_refused.get(reason_code, 0) + 1
        )
        self.fallback_reasons[msg] = self.fallback_reasons.get(msg, 0) + 1
        self._family_refusal_evidence(
            handle, prim_qid, reason_code, msg, details
        )

    def _find_family_primary(self, probe):
        """The running single-device sliced primary ``probe`` could attach
        to, or (None, None): registry lookup by correlated signature when
        the MQO is enabled, exact family signature otherwise (the PR-7
        posture)."""
        from ksql_tpu.runtime.device_executor import (
            DeviceExecutor,
            DistributedDeviceExecutor,
        )

        sig = (
            probe.correlated_signature() if self._mqo_enabled()
            else probe.family_signature()
        )
        if sig is None:
            return None, None
        prim_qid = self.window_families.get(sig)
        if prim_qid is None:
            return None, None
        prim = self.queries.get(prim_qid)
        if prim is None or not prim.is_running():
            return None, None
        pex = prim.executor
        if not isinstance(pex, DeviceExecutor) or isinstance(
            pex, DistributedDeviceExecutor
        ):
            return None, None  # sharing is single-device only
        if not getattr(pex.device, "sliced", False):
            return None, None
        return prim_qid, pex

    def _try_attach_family(self, handle, on_emit, on_query_error,
                           sliced_opt, ring_max):
        """Attach ``handle``'s plan to a running window-family primary when
        the correlated signature matches AND the cost model accepts;
        returns the member executor stub, or None to run the normal
        fallback ladder."""
        if not cfg._bool(
            self.effective_property(cfg.SLICING_SHARE_FAMILIES, True)
        ) or not self.window_families:
            return None
        if self.overload.defer_elective():
            # a family attach costs a compile; under CRITICAL overload the
            # standalone ladder (which reuses the admission-gated footprint)
            # is the cheaper, safer path — the query still starts
            self.fallback_reasons["overload-deferred"] = (
                self.fallback_reasons.get("overload-deferred", 0) + 1
            )
            return None
        from ksql_tpu.compiler.jax_expr import DeviceUnsupported
        from ksql_tpu.planner import mqo
        from ksql_tpu.runtime.device_executor import FamilyMemberExecutor
        from ksql_tpu.runtime.lowering import (
            CompiledDeviceQuery,
            FamilyAttachRefused,
        )

        try:
            probe = CompiledDeviceQuery(
                handle.plan, self.registry, capacity=1, analyze_only=True,
                sliced=sliced_opt, slice_ring_max=ring_max,
            )
        except Exception:  # noqa: BLE001 — not device-lowerable: ladder
            return None
        prim_qid, pex = self._find_family_primary(probe)
        if prim_qid is None or prim_qid == handle.query_id:
            return None
        if self._mqo_enabled():
            # the cost model prices the attach: marginal shared-ring bytes
            # (post-gcd width, union partial set) vs the member's
            # standalone footprint the admission gate already computed
            mem = getattr(handle, "mem_report", None)
            try:
                decision = mqo.decide_family_attach(
                    pex.device, probe,
                    primary_qid=prim_qid,
                    max_members=int(
                        self.effective_property(cfg.MQO_MAX_MEMBERS, 32)
                    ),
                    standalone_bytes=(
                        mem.per_shard_bytes() if mem is not None else None
                    ),
                    budget_bytes=int(
                        self.effective_property(cfg.MEMORY_BUDGET_BYTES, 0)
                        or 0
                    ),
                )
            except Exception as e:  # noqa: BLE001 — a cost-model failure
                self._on_error("mqo-decide", e)  # must not block the
                return None  # ladder: build standalone
            handle.mqo_decision = decision
            self._mqo_count(decision)
            if not decision.share:
                # stable reason CODE, not the human text: interpolated
                # primary qids would mint one Prometheus series per table
                # name (unbounded label cardinality)
                key = f"mqo-reject:{decision.reason_code}"
                self.fallback_reasons[key] = (
                    self.fallback_reasons.get(key, 0) + 1
                )
                if decision.reason_code in self._FAMILY_REFUSAL_CODES:
                    # the cost model pre-empted a runtime refusal: same
                    # loud, classified evidence lowering would emit
                    self._family_refusal_evidence(
                        handle, prim_qid, decision.reason_code,
                        decision.reason,
                    )
                return None
        member = FamilyMemberExecutor(
            handle.plan, self.broker, prim_qid,
            on_error=on_query_error, emit_callback=on_emit,
        )
        try:
            pex.device.attach_member(
                handle.plan, handle.query_id, member.deliver, probe=probe
            )
        except FamilyAttachRefused as e:
            # classified runtime refusal (the cost model normally pre-empts
            # these; a race with inflowing data can still land here)
            self._note_family_refusal(
                handle, prim_qid, e.reason_code, str(e), e.details
            )
            return None
        except DeviceUnsupported as e:
            self.fallback_reasons[str(e)] = (
                self.fallback_reasons.get(str(e), 0) + 1
            )
            return None
        except Exception as e:  # noqa: BLE001 — recompile failure etc.
            self._on_error("family-attach", e)
            return None
        self.family_members[handle.query_id] = prim_qid
        self._plog_append(
            f"mqo.attach:{handle.query_id}",
            f"window-family member of {prim_qid}",
        )
        return member

    def _try_attach_prefix(self, handle, on_emit, on_query_error):
        """Attach ``handle``'s stateless plan as a residual consumer of a
        running shared source-prefix pipeline (the push-registry tap seam
        lifted to persistent queries); returns the member executor stub,
        or None to run the normal fallback ladder."""
        if not self._mqo_enabled() or not cfg._bool(
            self.effective_property(cfg.MQO_SHARE_PREFIX, True)
        ) or not self.prefix_pipelines:
            return None
        if self.overload.defer_elective():
            # see _try_attach_family: elective compile deferred under
            # CRITICAL overload; the normal ladder still runs the query
            self.fallback_reasons["overload-deferred"] = (
                self.fallback_reasons.get("overload-deferred", 0) + 1
            )
            return None
        from ksql_tpu.compiler.jax_expr import DeviceUnsupported
        from ksql_tpu.planner import mqo
        from ksql_tpu.runtime.device_executor import (
            DeviceExecutor,
            DistributedDeviceExecutor,
            FamilyMemberExecutor,
        )
        from ksql_tpu.runtime.lowering import CompiledDeviceQuery

        try:
            probe = CompiledDeviceQuery(
                handle.plan, self.registry, capacity=1, analyze_only=True,
            )
            sig = probe.prefix_signature()
        except Exception:  # noqa: BLE001 — not device-lowerable: ladder
            return None
        if sig is None:
            return None
        prim_qid = self.prefix_pipelines.get(sig)
        if prim_qid is None or prim_qid == handle.query_id:
            return None
        prim = self.queries.get(prim_qid)
        if prim is None or not prim.is_running():
            return None
        pex = prim.executor
        if not isinstance(pex, DeviceExecutor) or isinstance(
            pex, DistributedDeviceExecutor
        ):
            return None  # sharing is single-device only
        mem = getattr(handle, "mem_report", None)
        try:
            decision = mqo.decide_prefix_attach(
                pex.device, probe,
                primary_qid=prim_qid,
                max_members=int(
                    self.effective_property(cfg.MQO_MAX_MEMBERS, 32)
                ),
                standalone_bytes=(
                    mem.per_shard_bytes() if mem is not None else None
                ),
            )
        except Exception as e:  # noqa: BLE001 — cost-model failure: ladder
            self._on_error("mqo-decide", e)
            return None
        handle.mqo_decision = decision
        self._mqo_count(decision)
        if not decision.share:
            self.fallback_reasons[decision.reason] = (
                self.fallback_reasons.get(decision.reason, 0) + 1
            )
            return None
        member = FamilyMemberExecutor(
            handle.plan, self.broker, prim_qid,
            on_error=on_query_error, emit_callback=on_emit,
        )
        try:
            pex.device.attach_prefix_member(
                handle.plan, handle.query_id, member.deliver, probe=probe
            )
        except DeviceUnsupported as e:
            self.fallback_reasons[str(e)] = (
                self.fallback_reasons.get(str(e), 0) + 1
            )
            return None
        except Exception as e:  # noqa: BLE001 — recompile failure etc.
            self._on_error("prefix-attach", e)
            return None
        self.family_members[handle.query_id] = prim_qid
        self._plog_append(
            f"mqo.attach:{handle.query_id}",
            f"prefix member of {prim_qid}",
        )
        return member

    def _register_family(self, handle, executor) -> None:
        """After a (re)build of a device executor: register a sliced
        single-device pipeline as its family's primary (or a shareable
        stateless pipeline as its prefix group's), and re-attach any
        members that were riding the replaced executor (restart path).

        Re-attach is pop-then-reattach under ONE engine-lock step: every
        rider leaves ``family_members`` BEFORE its attach is attempted and
        re-enters only on success, so a re-attach that raises after the
        primary swap can never orphan an entry pointing at a pipeline
        that holds no member spec (the orphan would be RUNNING but
        silent forever)."""
        from ksql_tpu.runtime.device_executor import (
            DeviceExecutor,
            DistributedDeviceExecutor,
            FamilyMemberExecutor,
        )

        if not isinstance(executor, DeviceExecutor) or isinstance(
            executor, DistributedDeviceExecutor
        ):
            return
        dev = executor.device
        sliced = bool(getattr(dev, "sliced", False))
        if sliced:
            sig = (
                dev.correlated_signature() if self._mqo_enabled()
                else dev.family_signature()
            )
            if sig is not None:
                self.window_families.setdefault(sig, handle.query_id)
        else:
            # a non-shareable rebuild still runs the rider loop below: a
            # rider that can no longer attach must promote loudly, never
            # linger in family_members pointing at a spec-less pipeline
            psig = dev.prefix_signature()
            if psig is not None and self._mqo_enabled() and cfg._bool(
                self.effective_property(cfg.MQO_SHARE_PREFIX, True)
            ):
                self.prefix_pipelines.setdefault(psig, handle.query_id)
        with self._lock:
            riders = [
                m_qid for m_qid, p_qid in self.family_members.items()
                if p_qid == handle.query_id
            ]
            for m_qid in riders:
                self.family_members.pop(m_qid, None)
        # the attach itself (re-layout + recompile, possibly a ring
        # regrow transfer) runs OUTSIDE the lock: a rider is absent from
        # family_members while its attach is in flight — the safe
        # direction (detach no-ops; nothing can observe a spec-less
        # registry entry)
        for m_qid in riders:
            mh = self.queries.get(m_qid)
            mex = getattr(mh, "executor", None)
            if mh is None or not isinstance(mex, FamilyMemberExecutor):
                continue
            try:
                if sliced:
                    dev.attach_member(mh.plan, m_qid, mex.deliver)
                else:
                    dev.attach_prefix_member(mh.plan, m_qid, mex.deliver)
                with self._lock:
                    self.family_members[m_qid] = handle.query_id
                self._plog_append(
                    f"mqo.attach:{m_qid}",
                    f"re-attached to rebuilt {handle.query_id}",
                )
            except Exception as e:  # noqa: BLE001 — member can no
                # longer share (ring constraints changed): promote it
                # through the normal restart ladder as a standalone
                # query; it already left family_members above
                self._on_error("family-reattach", e)
                mh.state = "ERROR"
                mh.retry_at_ms = 0.0

    def _detach_member_of(self, query_id: str) -> bool:
        """If ``query_id`` is a riding member (window family or source
        prefix), remove its spec from the primary's pipeline and the
        engine registry.  True if it was."""
        p_qid = self.family_members.pop(query_id, None)
        if p_qid is None:
            return False
        prim = self.queries.get(p_qid)
        dev = getattr(getattr(prim, "executor", None), "device", None)
        if dev is not None:
            for det in ("detach_member", "detach_prefix_member"):
                fn = getattr(dev, det, None)
                if fn is None:
                    continue
                try:
                    fn(query_id)
                except Exception as e:  # noqa: BLE001 — detach must never
                    self._on_error("family-detach", e)  # block the caller
        self._plog_append(
            f"mqo.evict:{query_id}", f"detached from {p_qid}"
        )
        return True

    def _release_family(self, query_id: str) -> List[str]:
        """Shared-pipeline bookkeeping for a query going away (terminate):
        detach a member from its primary, or unregister a primary (both
        registries) and return the member query ids that must be promoted
        to standalone executors."""
        if self._detach_member_of(query_id):
            return []
        promoted = []
        for sig, pq in list(self.window_families.items()):
            if pq == query_id:
                self.window_families.pop(sig, None)
        for sig, pq in list(self.prefix_pipelines.items()):
            if pq == query_id:
                self.prefix_pipelines.pop(sig, None)
        for m_qid, pq in list(self.family_members.items()):
            if pq == query_id:
                self.family_members.pop(m_qid, None)
                promoted.append(m_qid)
        return promoted

    def set_query_standby(self, query_id: str, standby: bool) -> None:
        """Demote to / promote from standby: a standby keeps materializing
        replica state but publishes nothing to its sink topic.  Promotion of
        a TABLE sink republishes the replica's current state — changes the
        dead active emitted-but-lost during the failover detection window
        surface as upserts (changelog-compaction equivalence)."""
        handle = self.queries.get(query_id)
        if handle is None or handle.standby == standby:
            return
        handle.standby = standby
        writer = getattr(handle.executor, "sink_writer", None)
        if writer is not None:
            writer.enabled = not standby
        if not standby and writer is not None and isinstance(
            handle.plan.physical_plan, st.TableSink
        ):
            from ksql_tpu.runtime.oracle import SinkEmit

            # replay with each row's original materialization timestamp —
            # downstream consumers must not observe rewritten ROWTIMEs
            # after failover (the reference's changelog keeps timestamps)
            for row, window, key, ts in list(handle.materialized.values()):
                writer.produce(SinkEmit(key, row, ts, window))

    @staticmethod
    def _now_ms() -> int:
        import time as _t

        return int(_t.time() * 1000)

    def _start_query(self, query_id: str, planned: PlannedQuery, sql: str,
                     mem_report=None) -> QueryHandle:
        source_topics = sorted(
            {step.topic for step in st.walk_steps(planned.plan.physical_plan)
             if isinstance(step, (st.StreamSource, st.WindowedStreamSource,
                                  st.TableSource, st.WindowedTableSource))}
        )
        for t in source_topics:
            self.broker.create_topic(t)
        self.annotate_serde_semantics(planned.plan)
        handle = QueryHandle(
            query_id=query_id,
            plan=planned.plan,
            sink_name=planned.plan.sink_name,
            executor=None,  # set below (needs materialization hook)
            consumer=Consumer(self.broker, source_topics),
            sql=sql,
            progress=qhealth.QueryProgress(
                query_id,
                history_size=int(
                    self.effective_property(cfg.HEALTH_HISTORY_SIZE, 256)
                ),
                stall_ticks=int(
                    self.effective_property(cfg.HEALTH_STALL_TICKS, 8)
                ),
            ),
        )

        handle.mem_report = mem_report
        try:
            handle.priority = int(
                self.effective_property(cfg.QUERY_PRIORITY, 100)
            )
        except (TypeError, ValueError):
            handle.priority = 100
        handle.executor = self._build_executor(handle)
        with self._lock:
            self.queries[query_id] = handle
        self.metastore.add_source_references(
            query_id,
            reads=list(planned.plan.source_names),
            writes=[planned.plan.sink_name] if planned.plan.sink_name else [],
        )
        return handle

    # ----------------------------------------------------------- checkpoint
    _last_checkpoint_ms = 0.0

    def checkpoint(self) -> Optional[str]:
        """Snapshot broker + query state to STATE_CHECKPOINT_DIR (the
        changelog-flush analog; see runtime/checkpoint.py)."""
        directory = self.effective_property(cfg.STATE_CHECKPOINT_DIR)
        if not directory:
            return None
        from ksql_tpu.runtime.checkpoint import save_checkpoint

        import time as _time

        path = save_checkpoint(self, str(directory))
        self._last_checkpoint_ms = _time.time() * 1000
        return path

    def restore_checkpoint(self) -> bool:
        """Load state saved by checkpoint() — call after WAL replay has
        re-created the queries (StoreChangelogReader restore analog)."""
        directory = self.effective_property(cfg.STATE_CHECKPOINT_DIR)
        if not directory:
            return False
        from ksql_tpu.runtime.checkpoint import restore_checkpoint

        ok = restore_checkpoint(self, str(directory))
        if ok:
            # a full restore moved state + offsets to the snapshot: any
            # in-memory epochs predate/postdate it inconsistently
            for h in self.queries.values():
                h.epoch = None
        return ok

    def _maybe_checkpoint(self) -> None:
        directory = self.effective_property(cfg.STATE_CHECKPOINT_DIR)
        if not directory:
            return
        import time as _time

        now = _time.time() * 1000
        interval = int(self.effective_property(cfg.CHECKPOINT_INTERVAL_MS, 30000))
        forced = getattr(self, "_changelog_force_ckpt", False)
        if forced or now - self._last_checkpoint_ms >= interval:
            # a still-overweight journal re-raises the flag on its next
            # append, so a failed forced save retries without spinning
            self._changelog_force_ckpt = False
            # checkpoints are engine-level (all queries snapshot together):
            # their stage lands on the __engine__ flight recorder
            rec = (
                self.trace_recorder(tracing.ENGINE_RECORDER)
                if self.trace_enabled else None
            )
            try:
                with tracing.tick(rec):
                    with tracing.span("checkpoint"):
                        self.checkpoint()
            except Exception as e:  # noqa: BLE001 — snapshot failure must
                self._on_error("checkpoint", e)  # not kill the poll loop

    # ------------------------------------------ incremental changelog
    def _changelog_for(self, handle: QueryHandle):
        """The query's journal (created lazily), or None when journaling
        is off: no checkpoint dir, or ksql.changelog.enable=false."""
        directory = self.effective_property(cfg.STATE_CHECKPOINT_DIR)
        if not directory:
            return None
        if not cfg._bool(self.effective_property(cfg.CHANGELOG_ENABLE, True)):
            return None
        cl = self._changelogs.get(handle.query_id)
        if cl is None:
            from ksql_tpu.runtime.changelog import QueryChangelog

            os.makedirs(str(directory), exist_ok=True)
            cl = QueryChangelog(
                str(directory), handle.query_id,
                fsync=cfg._bool(
                    self.effective_property(cfg.CHANGELOG_FSYNC, True)
                ),
            )
            self._changelogs[handle.query_id] = cl
        return cl

    def _changelog_append(self, handle: QueryHandle, executor,
                          consumer) -> None:
        """Tick commit point: journal the dirty-state delta + the tick's
        durable sink emissions (runtime/changelog.py).  Never raises —
        a journal failure degrades the query to the plain checkpoint
        posture, it must not kill the poll loop."""
        try:
            wtr = getattr(executor, "sink_writer", None)
            sink_records: list = []
            if wtr is not None and wtr.journal_buf:
                # drain even when the frame is skipped below, so the
                # capture buffer never grows across ticks
                sink_records = list(wtr.journal_buf)
                del wtr.journal_buf[:]
            cl = self._changelog_for(handle)
            if cl is None or cl.ckpt_id is None:
                # no generation to chain to yet: the query journals from
                # its first checkpoint rotation onward
                return
            from ksql_tpu.runtime import changelog as clog

            snap = clog.capture_query_state(
                handle, executor, consumer.positions
            )
            if snap is None:
                if handle.query_id not in self._changelog_skip_noted:
                    self._changelog_skip_noted.add(handle.query_id)
                    self._plog_append(
                        f"changelog.skip:{handle.query_id}",
                        "executor exposes no dirty-set seam; query keeps "
                        "the full-checkpoint recovery posture",
                    )
                return
            size = cl.append(snap, sink_records)
            try:
                max_bytes = int(self.effective_property(
                    cfg.CHANGELOG_MAX_BYTES, 16 * 2 ** 20
                ))
            except (TypeError, ValueError):
                max_bytes = 16 * 2 ** 20
            if max_bytes > 0 and size > max_bytes:
                # journal over its size cap: force an early checkpoint at
                # the next poll-loop gate (rotation truncates the file)
                self._changelog_force_ckpt = True
        except Exception as e:  # noqa: BLE001 — journaling is best-effort
            self._on_error(f"changelog.append:{handle.query_id}", e)

    def _changelog_rotate(self, ckpt_id: str,
                          queries: Dict[str, Any]) -> None:
        """save_checkpoint hook: the fresh snapshot covers every journal
        frame, so each query's journal truncates and re-chains to the new
        generation (its diff base becomes the just-saved snapshot)."""
        import time as _time

        self._ckpt_id = ckpt_id
        now = _time.time()
        for qid, snap in queries.items():
            self._checkpoint_saved_at[qid] = now
            handle = self.queries.get(qid)
            if handle is None:
                continue
            try:
                cl = self._changelog_for(handle)
                if cl is not None:
                    cl.arm(ckpt_id, snap, reset=True)
            except Exception as e:  # noqa: BLE001 — cleanup, not correctness:
                # stale frames chain to the OLD id and restore skips them
                self._on_error(f"changelog.append:{qid}", e)

    def _changelog_note_restore(self, handle: QueryHandle, info: Dict[str,
                                Any], ckpt_id: Optional[str], *,
                                startup: bool = True) -> None:
        """Restore-path hook (runtime/checkpoint.py): account the replay
        window, surface the tail replay on the timeline, and re-arm the
        journal to append after its intact prefix."""
        qid = handle.query_id
        try:
            from ksql_tpu.runtime import changelog as clog

            window = clog.replay_window(handle)
            handle.recovery_replayed_rows = (
                getattr(handle, "recovery_replayed_rows", 0) + window
            )
            if info.get("applied"):
                self._plog_append(
                    f"changelog.replay:{qid}",
                    f"replayed {info['applied']}/{info['total']} journal "
                    f"frames onto checkpoint generation {ckpt_id}; "
                    f"replay window {window} rows",
                )
                prog = getattr(handle, "progress", None)
                if prog is not None:
                    prog.note_event(
                        "changelog.replay",
                        frames=info["applied"], window=window,
                    )
            self._ckpt_id = ckpt_id
            cl = self._changelog_for(handle)
            if cl is not None:
                # a tail that failed to apply re-bases the journal: the
                # next frame is a FULL snapshot (shadow None), so later
                # recoveries never patch sparse deltas over skipped state
                shadow = None if info.get("fence") else info.get("qd")
                cl.arm(
                    ckpt_id, shadow, reset=False,
                    seq=int(info.get("last_seq") or 0),
                    good_size=int(info.get("good_size") or 0),
                )
        except Exception as e:  # noqa: BLE001 — accounting must not block
            self._on_error(f"changelog.replay:{qid}", e)

    def _install_function_limits(self) -> None:
        """ksql.functions.<name>.limit overrides (CollectListUdaf et al read
        their cap from config); scoped to this engine's processing tick."""
        import re as _re

        from ksql_tpu.functions import udafs as _udafs

        limits = {}
        merged = {**self.config.to_dict(), **self.session_properties}
        for k, v in merged.items():
            m = _re.fullmatch(r"ksql\.functions\.(\w+)\.limit", str(k))
            if m:
                limits[m.group(1).lower()] = v
        _udafs._LIMIT_OVERRIDES = limits

    # --------------------------------------------------------- run the loop
    def poll_once(self, max_records: int = 4096) -> int:
        """Drain available records through all running queries (synchronous
        scheduler tick).  Returns number of records processed.

        Delivery semantics: at-least-once.  Consumer offsets are
        snapshotted before each tick; when the query crashes mid-batch the
        offsets REWIND to the snapshot, so the self-healed restart replays
        the whole batch instead of silently dropping the unprocessed tail
        (the pre-fix behavior was at-most-once: poll had already advanced).
        Replay can duplicate sink records for the batch prefix — the same
        window Kafka Streams' at_least_once guarantee has.

        Poison records: a record whose processing raises a deterministic
        USER-classified error (bad cast, serde corruption, arithmetic) is
        skipped and logged to the processing log (the LogAndContinue
        analog) — replaying it forever would crash-loop the query without
        ever making progress."""
        self._install_function_limits()
        # overload sampling piggybacks on the poll loop (interval-gated,
        # never raises) so embedded engines get pressure monitoring
        # without a thread; under source pacing each query's tick is
        # clamped by priority below
        self.overload.maybe_sample()
        n = 0
        for handle in list(self.queries.values()):
            if handle.state == "ERROR":
                self._maybe_restart(handle)
            if handle.is_running():
                n += self._poll_query_supervised(
                    handle, self.overload.poll_rows(handle, max_records)
                )
            # health watchdog, piggybacked on the poll loop (no extra
            # thread in embedded mode): EVERY tick samples progress — the
            # failed/ERROR ticks included, because a crash-looping query
            # has frozen offsets under a growing topic, which is exactly
            # the stall signature the watchdog exists to catch
            self._health_sample(handle)
            # telemetry timeline gauge sample (interval-gated, never
            # raises): per-shard deltas, watermark lag, e2e-histogram
            # deltas, and any pending skew verdicts
            self._timeline_sample(handle)
            # elastic mesh: the rescale controller rides the same verdicts
            # (sustained LAGGING -> grow, sustained IDLE -> shrink);
            # default off, distributed queries only
            self._maybe_rescale(handle)
            # mesh fault domain: a degraded mesh probes back toward its
            # original width once the fault has stayed clear
            self._maybe_mesh_regrow(handle)
        if n:
            self._maybe_checkpoint()
        return n

    def _poll_query_supervised(self, handle: QueryHandle,
                               max_records: int) -> int:
        """Run the query's tick body, under a deadline-supervised worker
        when ``ksql.query.tick.timeout.ms`` is set.  A tick that blows the
        deadline is abandoned (the worker keeps running but is fenced off:
        forked consumer, muted sink), the query is marked STALLED with
        ``tick.deadline`` evidence, and the restart ladder takes over —
        sibling queries keep polling instead of stalling behind the hang."""
        timeout_ms = float(
            self.effective_property(cfg.QUERY_TICK_TIMEOUT_MS, 0) or 0
        )
        if timeout_ms <= 0:
            return self._poll_query(handle, max_records)
        try:
            if handle.consumer.at_end():
                # idle tick: nothing to poll, nothing buffered across ticks
                # (drain runs every tick) — skip the worker entirely rather
                # than churn a thread per query per empty tick
                return 0
        except Exception:  # noqa: BLE001 — topic gone mid-flight: let the
            pass  # supervised tick surface the real error
        result: Dict[str, Any] = {}

        def body():
            try:
                result["n"] = self._poll_query(handle, max_records)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                result["err"] = e

        # persistent per-query worker (amortizes the per-tick thread
        # spawn); done.wait is the join-equivalent — a blown deadline
        # abandons the worker, which exits after its hung tick, and the
        # next tick gets a fresh one
        worker = self._tick_workers.get(handle.query_id)
        if worker is None or not worker.alive():
            worker = _TickSupervisionWorker(handle.query_id)
            self._tick_workers[handle.query_id] = worker
        done = worker.submit(body)
        if not done.wait(timeout_ms / 1000.0):
            worker.abandon()
            self._tick_workers.pop(handle.query_id, None)
            # prune zombies that already exited before remembering this
            # one: the list must stay bounded by LIVE zombies, not by
            # deadline incidents over the engine's lifetime
            self._abandoned_workers = [
                w for w in self._abandoned_workers if w.thread.is_alive()
            ]
            self._abandoned_workers.append(worker)
            self._tick_deadline_exceeded(handle, timeout_ms)
            return 0
        err = result.get("err")
        if err is not None:
            raise err
        return int(result.get("n", 0))

    def _stop_tick_worker(self, query_id: str) -> None:
        """TERMINATE/DROP path: shut down and join the query's persistent
        supervision worker (no-op when supervision never armed)."""
        w = self._tick_workers.pop(query_id, None)
        if w is not None:
            w.stop()

    def shutdown(self, join_timeout_s: float = 15.0) -> None:
        """Stop and join THIS engine's supervision workers (embedded-mode
        teardown).  A daemon worker killed by interpreter exit while it is
        inside an XLA dispatch aborts the whole process ('terminate called
        without an active exception'), so hosts that armed
        ``ksql.query.tick.timeout.ms`` should call this before exiting;
        abandoned zombies still wedged in a hung tick get a bounded join."""
        import time as _time

        # stop the overload monitor thread (server mode) before the
        # queries it samples go away
        self.overload.stop()
        if self.push_registry is not None:
            # shared push pipelines hold broker consumers and (listener
            # mode) handle callbacks: tear them down before the queries go
            self.push_registry.stop_all()
        for qid in list(self._tick_workers):
            self._stop_tick_worker(qid)
        deadline = _time.time() + join_timeout_s
        for w in self._abandoned_workers:
            w.thread.join(max(0.0, deadline - _time.time()))
        self._abandoned_workers = [
            w for w in self._abandoned_workers if w.thread.is_alive()
        ]

    def _tick_deadline_exceeded(self, handle: QueryHandle,
                                timeout_ms: float) -> None:
        """The supervised tick hung: fence off the abandoned worker and
        recover.  The zombie keeps references to the old consumer (forked
        away here), the old executor (sink muted here, replaced by the
        restart), and the tick-local commit dict (reallocated next tick) —
        its late writes land on orphans, and the guarded mutation points in
        ``_poll_query`` no-op once ``handle.consumer`` changed.  Sink rows
        the worker produced before hanging stay durable; the restart
        replays from the commit point, so the duplicate window is the
        usual at-least-once one."""
        handle.tick_deadlines += 1
        old = handle.consumer
        commit = dict(handle.commit_positions or old.positions)
        handle.replayed_records += sum(
            max(pos - commit.get(k, pos), 0)
            for k, pos in old.positions.items()
        )
        handle.consumer = old.fork(commit)
        writer = getattr(handle.executor, "sink_writer", None)
        if writer is not None:
            writer.enabled = False  # a woken zombie must not publish
        if getattr(handle.executor, "emit_callback", None) is not None:
            # ...nor write stale rows into the shared materialization
            # shadow / push listeners through the orphan's emit callback
            handle.executor.emit_callback = None
        if handle.emit_fence is not None:
            # the zombie may already hold the callback reference (read
            # before the null above landed); the fence kills the callback
            # body itself, so even an in-flight dispatch loop cannot write
            # stale handle.materialized entries
            handle.emit_fence["live"] = False
        if handle.progress is not None:
            handle.progress.note_tick_deadline(int(timeout_ms))
        self._plog_append(
            f"tick.deadline:{handle.query_id}",
            f"tick exceeded {cfg.QUERY_TICK_TIMEOUT_MS}={int(timeout_ms)}ms;"
            " worker abandoned, query scheduled for restart",
        )
        exc = KsqlException(
            f"tick deadline exceeded ({cfg.QUERY_TICK_TIMEOUT_MS}="
            f"{int(timeout_ms)}ms): worker abandoned, replaying from the "
            "last commit point after restart"
        )
        # mesh fault domain: a distributed dispatch wedged inside ONE
        # shard's lane (hang at mesh.shard.dispatch) leaves the runner's
        # suspect-shard marker set — stamp the deadline error with it so
        # the strike bookkeeping can contain the failure to that shard
        sus = getattr(handle.executor, "suspect_shard", None)
        if callable(sus):
            try:
                shard = sus()
            except Exception:  # noqa: BLE001 — attribution is best-effort
                shard = None
            if shard is not None:
                exc.mesh_shard = int(shard)
                exc.mesh_deadline = True
        self._query_failed(handle, exc)

    def _poll_query(self, handle: QueryHandle, max_records: int) -> int:
        """One query's poll tick (the poll/process/drain body of
        ``poll_once``); returns records processed.

        Processing epochs (``ksql.commit.per.record``, default on): the
        tick is a sequence of durable sub-commits, not an all-or-nothing
        batch.  A ``commit`` cursor trails the records whose sink emissions
        are durable (on micro-batched executors, whatever
        ``pending_records()`` has not flushed stays uncommitted); a crash
        rewinds to the commit point, replaying only the non-durable tail.
        On the record-synchronous oracle backend a per-record state epoch
        rides along, so the restart restores state matching the commit
        point and a poison record rolls stores back to its pre-record
        epoch before being skipped (atomic poison skip).  Micro-batched
        backends cannot roll a store back one record, so an attributable
        poison record is instead dropped on replay
        (``handle.poison_skip`` — replay-without-record)."""
        import time as _time

        n = 0
        # poison bisection (non-attributable poison in a batched flush): a
        # previous crash halved the window this tick may poll, so the
        # deterministic crash point converges on ONE record — which is
        # attributable and skipped atomically
        if handle.poison_bisect is not None:
            max_records = max(
                1, min(max_records,
                       int(handle.poison_bisect.get("limit", max_records)))
            )
        # identity-bind consumer/executor: if the deadline watchdog abandons
        # this tick, the handle gets a forked consumer and every handle
        # mutation below must be suppressed (zombie-worker fence)
        consumer = handle.consumer
        executor = handle.executor
        offsets_before = dict(consumer.positions)
        per_record = cfg._bool(
            self.effective_property(cfg.COMMIT_PER_RECORD, True)
        )
        commit = dict(offsets_before)
        # tick-START binding, before this worker can possibly be abandoned
        # (the supervisor only fences after the deadline elapses) — the one
        # handle write that must run unfenced
        handle.commit_positions = commit  # graftlint: disable=unfenced-handle-mutation
        pending_fn = getattr(executor, "pending_records", None)
        stateful = bool(getattr(executor, "stateful", False))
        epoch_capable = (
            per_record and stateful and hasattr(executor, "state_epoch")
        )
        # consumed entries: (topic, partition, offset, handed_idx) —
        # handed_idx is None for records SKIPPED without entering the
        # executor (replay-without-record), which are durable immediately;
        # a handed record is durable once the executor has flushed it
        # (its handed_idx < handed - pending()).  The commit cursor only
        # advances over a contiguous durable prefix, so a skip sitting
        # between still-buffered records can never commit them early.
        consumed: List[Tuple[str, int, int, Optional[int]]] = []
        committed_idx = 0
        handed = 0
        # per-record state epochs degrade gracefully on big state: once one
        # snapshot blows the budget, epochs (and with them the commit
        # cursor of epoch-capable queries) go per-TICK instead of
        # per-record — correctness keeps, the replay window widens
        epoch_budget_ms = float(
            self.effective_property(cfg.EPOCH_SNAPSHOT_BUDGET_MS, 2.0)
        )
        epoch_ok = True
        last_epoch_handed = -1

        def alive() -> bool:
            return handle.consumer is consumer

        def pending() -> int:
            return pending_fn() if pending_fn is not None else 0

        def advance_commit() -> None:
            nonlocal committed_idx
            durable_handed = handed - pending()
            while committed_idx < len(consumed):
                tn_, p_, off_, hidx = consumed[committed_idx]
                if hidx is not None and hidx >= durable_handed:
                    break
                commit[(tn_, p_)] = off_ + 1
                committed_idx += 1

        def take_epoch_budgeted() -> None:
            nonlocal epoch_ok, last_epoch_handed
            t0 = _time.perf_counter()
            self._take_epoch(handle, executor, alive, commit)
            last_epoch_handed = handed
            if (_time.perf_counter() - t0) * 1000.0 > epoch_budget_ms:
                epoch_ok = False

        def note_durable() -> None:
            """Advance the commit cursor past newly-durable records, taking
            a matching state epoch when the query needs one (per record
            while snapshots stay in budget; the end-of-tick pass amortizes
            otherwise)."""
            if not per_record:
                return
            if epoch_capable and not epoch_ok:
                return  # commit holds at the last epoch point mid-tick
            before = committed_idx
            advance_commit()
            if epoch_capable and committed_idx > before:
                take_epoch_budgeted()

        def replay_window() -> int:
            """Records a rewind-to-commit would replay (polled offsets
            beyond the commit cursor) — the poison-bisection window."""
            return sum(
                max(pos - commit.get(k, pos), 0)
                for k, pos in consumer.positions.items()
            )

        def rewind_to_commit() -> None:
            replay = replay_window()
            consumer.positions.update(commit)
            if alive():
                handle.replayed_records += replay

        # flight recorder: one tick trace per query per poll (empty
        # ticks are discarded so the ring holds real work); tick(None)
        # when tracing is disabled — the instrumented seams then reduce
        # to a single thread-local None check
        rec = (
            self.trace_recorder(handle.query_id)
            if self.trace_enabled else None
        )
        with tracing.tick(rec) as tick:
            try:
                with tracing.span("poll"):
                    records = consumer.poll(max_records)
            except Exception as e:  # noqa: BLE001 — a torn read advanced
                # some positions already: rewind so nothing is dropped
                rewind_to_commit()
                if alive():
                    self._query_failed(handle, e)
                return 0
            if tick is not None:
                tick.keep = bool(records)
                # rows accounting for the telemetry timeline fold (the
                # trace itself is the transport; no extra plumbing)
                tick.counter("poll", rows=len(records))
            if records and handle.progress is not None:
                # event-time watermark: max record timestamp consumed
                handle.progress.note_watermark(
                    max(r.timestamp for _, r in records)
                )
            if epoch_capable and records:
                # the epoch matching the tick-start commit point (and the
                # pre-record store snapshot the first record's poison
                # rollback needs)
                take_epoch_budgeted()
            tick0 = _time.monotonic()
            with tracing.span("process"):
                for topic, rec_ in records:
                    rkey = (topic, rec_.partition, rec_.offset)
                    if rkey in handle.poison_skip:
                        # replay-without-record: this record poisoned a
                        # previous attempt on a micro-batched backend; the
                        # replay drops it so state never re-absorbs it
                        if alive():
                            handle.poison_skip.discard(rkey)
                        self._on_error(
                            f"poison:{handle.query_id}:{topic}",
                            KsqlException(
                                "replay-without-record: skipping poison "
                                f"record {topic}-{rec_.partition}"
                                f"@{rec_.offset}"
                            ),
                        )
                        if tick is not None:
                            tick.stage("poison.skip", 0.0)
                        consumed.append((*rkey, None))
                        n += 1
                        note_durable()
                        continue
                    # computed regardless of the commit knob: poison
                    # attribution (below) must not blame the flush-trigger
                    # record for a batched flush error when earlier records
                    # are still buffered
                    pending_before = pending()
                    try:
                        executor.process(topic, rec_)
                    except Exception as e:  # noqa: BLE001
                        if self._is_poison(e):
                            is_oracle = handle.backend == "oracle"
                            record_sync = is_oracle or bool(
                                getattr(executor, "record_synchronous",
                                        False)
                            )
                            # atomic rollback needs an epoch matching the
                            # EXACT pre-record state (taken after the last
                            # handed record); a stale epoch must not
                            # un-absorb earlier records' state
                            rolled = (
                                stateful and epoch_capable
                                and handed == last_epoch_handed
                                and self._rollback_epoch(
                                    handle, executor, alive
                                )
                            )
                            if record_sync and (not stateful or rolled):
                                # atomic in-place skip: stores rolled back
                                # to the pre-record epoch (stateless paths
                                # have nothing to diverge)
                                self._on_error(
                                    f"poison:{handle.query_id}:{topic}", e
                                )
                                self.metrics.for_query(
                                    handle.query_id
                                ).errors.mark(1)
                                if tick is not None:
                                    tick.stage("poison.skip", 0.0)
                                handed += 1
                                consumed.append((*rkey, handed - 1))
                                n += 1  # offset advanced: skipping IS
                                note_durable()  # progress
                                continue
                            if is_oracle and not epoch_capable:
                                # legacy PR-1 posture (commit-per-record
                                # off): skip in place, absorbed state
                                # stands — the documented one-record
                                # divergence, preferred over crash-looping
                                self._on_error(
                                    f"poison:{handle.query_id}:{topic}", e
                                )
                                self.metrics.for_query(
                                    handle.query_id
                                ).errors.mark(1)
                                if tick is not None:
                                    tick.stage("poison.skip", 0.0)
                                handed += 1
                                consumed.append((*rkey, handed - 1))
                                n += 1
                                continue
                            if (record_sync or pending_before == 0) \
                                    and alive():
                                # attributable to exactly this record, but
                                # its state absorption cannot roll back:
                                # restart and replay WITHOUT the record
                                handle.poison_skip.add(rkey)
                                self._on_error(
                                    f"poison:{handle.query_id}:{topic}",
                                    KsqlException(
                                        "poison record will be dropped on "
                                        f"replay: {type(e).__name__}: {e}"
                                    ),
                                )
                            elif alive():
                                # NON-attributable: earlier records are
                                # still buffered in the batched flush, any
                                # of them may be the poison — halve the
                                # replay window for the next attempt
                                self._note_poison_bisect(
                                    handle, replay_window()
                                )
                        rewind_to_commit()
                        if alive():
                            self._query_failed(handle, e)
                        return n
                    handed += 1
                    consumed.append((*rkey, handed - 1))
                    n += 1
                    note_durable()
            try:
                drain = getattr(executor, "drain", None)
                if drain is not None:
                    # flush the device executor's partial micro-batch
                    with tracing.span("drain"):
                        drain()
            except Exception as e:  # noqa: BLE001 — a crashing query must
                # not take down the engine; rewind so the restart replays
                if self._is_poison(e) and alive():
                    # a deterministic USER error inside the batched device
                    # flush: no single record is attributable — unless
                    # bisection already narrowed the window to one
                    nondurable = consumed[committed_idx:]
                    if len(nondurable) == 1 and replay_window() == 1:
                        rk = nondurable[0][:3]
                        handle.poison_skip.add(rk)
                        handle.poison_bisect = None
                        self._on_error(
                            f"poison:{handle.query_id}:{rk[0]}",
                            KsqlException(
                                "poison record isolated by replay-window "
                                "bisection; dropped on replay: "
                                f"{type(e).__name__}: {e}"
                            ),
                        )
                    else:
                        self._note_poison_bisect(handle, replay_window())
                rewind_to_commit()
                if alive():
                    self._query_failed(handle, e)
                return n
            if per_record and consumed:
                # drained: every consumed record's emissions are durable.
                # This end-of-tick pass also amortizes the state epoch for
                # queries whose per-record snapshots blew the budget —
                # one epoch per tick keeps commit == epoch consistent.
                before = committed_idx
                advance_commit()
                if epoch_capable and committed_idx > before and alive():
                    take_epoch_budgeted()
            if records:
                if not alive():
                    return n  # abandoned mid-tick: the fence owns the rest
                # a healthy tick after a restart closes the incident: the
                # retry budget bounds CONSECUTIVE failures (crash-loops),
                # not unrelated transient faults across the query lifetime
                if handle.restart_count:
                    handle.restart_count = 0
                    handle.retry_backoff_ms = 0.0
                if handle.shard_strikes:
                    # consecutive-strike semantics: a clean tick clears
                    # every suspect shard's streak (lifetime totals keep)
                    handle.shard_strikes = {}
                if handle.poison_bisect is not None:
                    # a clean tick ends the bisection: full-size polls
                    # resume (a later crash re-derives its own window)
                    handle.poison_bisect = None
                # tick commit point: everything above is durable in the
                # in-memory sense — journal the dirty-state delta + this
                # tick's sink emissions (runtime/changelog.py) so a kill
                # -9 replays ticks-since-last-checkpoint, not the batch
                self._changelog_append(handle, executor, consumer)
                qm = self.metrics.for_query(handle.query_id)
                qm.messages_in.mark(len(records))
                qm.latency.record(_time.monotonic() - tick0)
                qm.last_message_at_ms = int(_time.time() * 1000)
        return n

    # ------------------------------------------------------- state epochs
    def _take_epoch(self, handle: QueryHandle, executor, alive=None,
                    commit=None) -> None:
        """Snapshot the record-synchronous executor's state as the current
        commit-point epoch, together with the host materialization shadow
        (which the emit callback mutates before a sink produce can fail).
        The epoch carries the commit positions it was taken at: the restart
        path only restores an epoch whose positions equal the consumer's
        rewound positions, so a fenced-off zombie worker racing a late
        epoch in (state ahead of the fork point) can never double-count."""
        try:
            ep = {
                "backend": handle.backend,
                "state": executor.state_epoch(),
                "materialized": dict(handle.materialized),
                "positions": dict(
                    commit if commit is not None else handle.consumer.positions
                ),
                # sink ordinal high-water rides the epoch so a rebuilt
                # executor's fresh SinkWriter continues the sequence —
                # changelog frames (runtime/changelog.py) stay monotone
                # across in-memory self-heals
                "emit_seq": int(getattr(
                    getattr(executor, "sink_writer", None), "emit_seq", 0
                ) or 0),
            }
        except Exception as e:  # noqa: BLE001 — an unsnapshottable state
            # drop the PREVIOUS epoch too: the commit cursor keeps
            # advancing, and restoring a stale epoch against newer offsets
            # would silently lose records from state — degrading to the
            # disk checkpoint is the consistent fallback
            self._on_error("epoch-snapshot", e)
            if alive is None or alive():
                handle.epoch = None
            return
        if alive is None or alive():
            handle.epoch = ep

    def _rollback_epoch(self, handle: QueryHandle, executor,
                        alive=None) -> bool:
        """Roll executor stores (and the materialization shadow) back to
        the last per-record epoch — the atomic-poison-skip undo.  Returns
        True when the rollback happened.  The materialization shadow is
        shared handle state, so an abandoned tick worker (``alive`` false)
        may only roll back its own orphaned executor, never the shadow."""
        ep = handle.epoch
        if (
            ep is None or ep.get("state") is None
            or ep.get("backend") != handle.backend
            or not hasattr(executor, "restore_state_epoch")
        ):
            return False
        try:
            executor.restore_state_epoch(ep["state"])
        except Exception as e:  # noqa: BLE001 — a failed undo must not
            self._on_error("epoch-rollback", e)  # mask the poison handling
            return False
        if ep.get("materialized") is not None and (alive is None or alive()):
            handle.materialized.clear()
            handle.materialized.update(ep["materialized"])
        return True

    # --------------------------------------------------- health / watchdog
    def _health_sample(self, handle: QueryHandle) -> None:
        """One watchdog sample for the query: refresh offsets/lag/watermark
        and classify HEALTHY/IDLE/LAGGING/STALLED.  RUNNING and ERROR
        queries sample (an error-backoff tick with frozen offsets is stall
        evidence); PAUSED/TERMINATED queries are deliberately not judged."""
        prog = handle.progress
        if prog is None or handle.state not in ("RUNNING", "ERROR"):
            return
        # fold in the executor's decoded event time: with a TIMESTAMP
        # column the event-time watermark can run ahead of (or behind) the
        # raw record timestamps the poll loop saw
        st = getattr(handle.executor, "stream_time", None)
        if st is not None and st > -(2 ** 62):
            prog.note_watermark(int(st))
        prog.sample(handle.consumer)

    def _timeline_sample(self, handle: QueryHandle) -> None:
        """One interval-gated telemetry gauge sample for the query:
        per-shard cumulative stats, watermark lag, and the e2e histogram
        fold into the timeline as interval deltas; then any skew verdicts
        the interval close produced are published (``telemetry.skew:<qid>``
        plog, watchdog evidence event, and the engine-level
        ``telemetry_events`` ring the /alerts "telemetry" section reads)."""
        if not self.telemetry_enabled:
            return
        import time as _time

        qid = handle.query_id
        tl = self.timelines.get(qid)
        if tl is None:
            # nothing folded yet (query has not ticked): no series to
            # gauge, and creating a store here would grow one per
            # never-ticking query
            return
        now_ms = int(_time.time() * 1000)
        if tl.gauge_due(now_ms):
            shards = None
            shard_fn = getattr(handle.executor, "shard_metrics", None)
            if shard_fn is not None:
                try:
                    shards = shard_fn()
                except Exception:  # noqa: BLE001 — telemetry must never
                    shards = None  # take down the poll loop
            prog = handle.progress
            lag_ms = None
            e2e = None
            if prog is not None:
                if prog.watermark_ms is not None:
                    lag_ms = now_ms - int(prog.watermark_ms)
                hist = getattr(prog, "e2e_hist", None)
                if hist is not None and hist.count:
                    e2e = hist.snapshot()
            tl.observe(
                now_ms, shards=shards, watermark_lag_ms=lag_ms, e2e=e2e
            )
        for ev in tl.drain_events():
            detail = (
                f"hot shard {ev['hotShard']} carries {ev['share']:.0%} "
                f"of {ev['metric']} over {ev['intervals']} intervals"
            )
            # the plog entry routes back through _timeline_annotate, so
            # the skew verdict is ALSO visible on the timeline it judged
            self._plog_append(f"telemetry.skew:{qid}", detail)
            prog = handle.progress
            if prog is not None:
                try:
                    prog.note_event(
                        "telemetry.skew",
                        hotShard=ev["hotShard"], share=ev["share"],
                        metric=ev["metric"], intervals=ev["intervals"],
                    )
                except Exception:  # noqa: BLE001
                    pass
            self.telemetry_events.append({
                "queryId": qid, "detail": detail, **ev,
            })

    def health_alerts(self) -> List[Dict[str, Any]]:
        """Current LAGGING/STALLED queries with their evidence — the body
        of ``GET /alerts`` (and the embedded-mode equivalent the chaos
        soak's ``--watch`` polls)."""
        out = []
        for qid, h in list(self.queries.items()):
            prog = h.progress
            if prog is None or prog.health not in qhealth.ALERT_STATES:
                continue
            out.append(prog.alert(h.state, {
                "terminal": h.terminal,
                "restarts": h.restart_count,
                "backend": h.backend,
            }))
        return out

    # ------------------------------------------------ elastic mesh rescale
    def _maybe_rescale(self, handle: QueryHandle) -> None:
        """Health-driven live rescale controller (``ksql.rescale.enable``,
        default off): a distributed query whose watchdog verdict holds
        LAGGING for ``ksql.rescale.hysteresis.ticks`` consecutive samples
        doubles its mesh toward ``ksql.device.shards.max``; IDLE for the
        same streak halves it toward ``ksql.device.shards.min``.  A
        cooldown (``ksql.rescale.cooldown.ms``) separates consecutive
        cutovers so a grow observes its effect before the controller may
        act again."""
        import time as _time

        if not cfg._bool(self.effective_property(cfg.RESCALE_ENABLE, False)):
            return
        if self.overload.defer_elective():
            return  # a rescale cutover costs a compile: not under CRITICAL
        prog = handle.progress
        if (
            handle.state != "RUNNING" or handle.backend != "distributed"
            or handle.pending_rescale is not None or prog is None
        ):
            handle.rescale_lag_streak = 0
            handle.rescale_idle_streak = 0
            return
        health = prog.health
        handle.rescale_lag_streak = (
            handle.rescale_lag_streak + 1 if health == qhealth.LAGGING else 0
        )
        handle.rescale_idle_streak = (
            handle.rescale_idle_streak + 1 if health == qhealth.IDLE else 0
        )
        hyst = int(self.effective_property(cfg.RESCALE_HYSTERESIS_TICKS, 8))
        cooldown = float(
            self.effective_property(cfg.RESCALE_COOLDOWN_MS, 60000)
        ) * max(1, handle.rescale_penalty)
        if _time.time() * 1000 - handle.last_rescale_ms < cooldown:
            return
        cur = int(getattr(
            getattr(handle.executor, "device", None), "n_shards", 0
        ) or 0)
        if not cur:
            return
        import jax as _jax

        smax = int(
            self.effective_property(cfg.DEVICE_SHARDS_MAX, 0) or 0
        ) or len(_jax.devices())
        smin = max(1, int(self.effective_property(cfg.DEVICE_SHARDS_MIN, 1)))
        if handle.rescale_lag_streak >= hyst and cur < smax:
            self._rescale_query(handle, min(cur * 2, smax), "grow")
        elif handle.rescale_idle_streak >= hyst and cur > smin:
            target = max(cur // 2, smin)
            if self._shrink_overflows_budget(handle, target):
                # refused, loudly: arm the cooldown + clear the streak so
                # the controller does not re-price the same shrink every
                # poll tick while the query stays IDLE
                handle.rescale_idle_streak = 0
                handle.last_rescale_ms = _time.time() * 1000
                return
            self._rescale_query(handle, target, "shrink")

    def _shrink_overflows_budget(self, handle: QueryHandle,
                                 target: int) -> bool:
        """Memory-model guard on mesh shrink (closing half the ROADMAP
        'doubles/halves blindly' gap): a shrink concentrates every key
        onto fewer shards and reshard-on-restore grows the per-shard
        store until the fullest target shard sits at <= 50% load — price
        THAT footprint with the static model before paying the cutover,
        and refuse when it would overflow
        ``ksql.analysis.memory.budget.bytes``."""
        budget = int(
            self.effective_property(cfg.MEMORY_BUDGET_BYTES, 0) or 0
        )
        if not budget:
            return False
        dev = getattr(handle.executor, "device", None)
        compiled = getattr(dev, "c", dev)  # DistributedDeviceQuery wraps
        if compiled is None:
            return False
        try:
            import jax as _jax
            import numpy as _np

            from ksql_tpu.analysis.mem_model import (
                POINT_CREATION,
                shrink_footprint,
            )

            occ = dev.state.get("occ") if hasattr(dev, "state") else None
            live = 0
            if occ is not None:
                # host readback of the occupancy bitmask only (bools, one
                # per slot) — the controller runs at poll-tick cadence
                # and ONLY when a shrink is already due
                live = int(_np.asarray(
                    _jax.device_get(occ)
                )[..., :-1].sum())
            proj = shrink_footprint(
                compiled, live, target, growth_budget_bytes=budget
            )
            need = proj.per_shard_bytes(POINT_CREATION)
        except Exception as e:  # noqa: BLE001 — a pricing failure must
            # not wedge the controller; the cutover keeps its own
            # refuse-loudly reshard guards
            self._on_error("rescale-memcheck", e)
            return False
        if need <= budget:
            return False
        dom = proj.dominant(POINT_CREATION)
        store_cap = next(
            (c.capacity for c in proj.components if c.name == "store"), 0
        )
        self._plog_append(
            f"rescale.refuse:{handle.query_id}",
            f"shrink to {target} shard(s) refused by the memory model: "
            f"{live} live keys concentrate to a per-shard store of "
            f"{store_cap} slots, projected footprint {need} bytes > "
            f"{cfg.MEMORY_BUDGET_BYTES}={budget}"
            + (f"; dominant component {dom.name}={dom.at_creation}B"
               if dom is not None else ""),
        )
        if handle.progress is not None:
            handle.progress.note_event(
                "rescale.refuse", target=target,
                projectedBytes=int(need), budgetBytes=int(budget),
                dominant=dom.name if dom is not None else "",
            )
        return True

    def _rescale_query(self, handle: QueryHandle, target: int,
                       direction: str) -> None:
        """Execute one resize as a supervised drain/cutover riding the
        restart ladder: commit-point checkpoint (the poll loop is between
        ticks here, so the executor is drained and the commit point equals
        the consumer positions) -> route through ``_maybe_restart`` with
        zero backoff, which fences the old executor (emit-fence swap +
        rebuild-token identity: a wedged old mesh becomes a muted zombie
        exactly like an abandoned rebuild), rebuilds at ``target`` shards,
        reshard-restores the checkpoint, and resumes from the commit
        point.  The rebuild deadline and the retry ladder are the failure
        path; a failed cutover reverts to the previous shard count."""
        import time as _time

        cur = int(getattr(
            getattr(handle.executor, "device", None), "n_shards", 0
        ) or 0)
        if target == cur or target < 1:
            return
        directory = self.effective_property(cfg.STATE_CHECKPOINT_DIR)
        stateful = bool(getattr(handle.executor, "stateful", False))
        if stateful and not directory:
            # stateful state can only cross meshes through the checkpoint
            # tier: without a directory the cutover would silently
            # cold-start the aggregation — refuse, loudly
            self._plog_append(
                f"rescale.no-checkpoint:{handle.query_id}",
                f"cannot {direction} {cur}->{target} shards: stateful "
                f"query and no {cfg.STATE_CHECKPOINT_DIR}; set it to "
                "enable elastic rescale",
            )
            handle.rescale_lag_streak = 0
            handle.rescale_idle_streak = 0
            handle.last_rescale_ms = _time.time() * 1000
            return
        init_phases: Dict[str, float] = {}
        if directory:
            # take the commit-point checkpoint UNCONDITIONALLY (stateless
            # queries included): the rebuild's restore path loads the last
            # snapshot's positions, and a stale periodic snapshot would
            # rewind a stateless query up to checkpoint.interval.ms of
            # offsets — re-emitting every record since it into the sink.
            # Both initiation phases land on the query's flight recorder
            # as cutover.* spans; their durations ride pending_rescale so
            # the rescale.done evidence event reports the WHOLE cutover
            # phase-by-phase (a slow cutover is attributable to a phase,
            # not a wall-clock blob)
            rec = self.recorder_if_enabled(handle.query_id)
            try:
                with tracing.tick(rec) as tk:
                    with tracing.span("cutover.drain"):
                        # the poll loop is between ticks, so this is a
                        # no-op flush — kept explicit so the commit-point
                        # invariant is enforced, not assumed
                        drain = getattr(handle.executor, "drain", None)
                        if drain is not None:
                            drain()
                    with tracing.span("cutover.checkpoint"):
                        self.checkpoint()  # the cutover's commit point
                    if tk is not None:
                        init_phases = {
                            name: round(st.get("ms", 0.0), 3)
                            for name, st in tk.stages.items()
                            if name.startswith("cutover.")
                        }
            except Exception as e:  # noqa: BLE001 — no snapshot, no cutover
                self._on_error("rescale-checkpoint", e)
                # arm the cooldown + clear the streaks like any other
                # aborted attempt: without this the controller would retry
                # a FULL engine checkpoint every poll tick in a tight loop
                handle.rescale_lag_streak = 0
                handle.rescale_idle_streak = 0
                handle.last_rescale_ms = _time.time() * 1000
                return
        handle.pending_rescale = {
            "target": target, "from": cur, "direction": direction,
            "prev_override": handle.shard_override,
            "phases": init_phases,
        }
        handle.shard_override = target
        handle.last_rescale_ms = _time.time() * 1000
        handle.rescale_lag_streak = 0
        handle.rescale_idle_streak = 0
        self._plog_append(
            f"rescale:{handle.query_id}",
            f"{direction} {cur}->{target} shards: supervised drain/cutover "
            "via the restart ladder",
        )
        if handle.progress is not None:
            handle.progress.note_event(
                f"rescale.{direction}", **{"from": cur, "to": target}
            )
        # drained cutover: between ticks nothing is buffered, so ERROR +
        # zero backoff hands the query to _maybe_restart on the next poll
        # iteration — rebuild supervision (deadline, fences) applies
        # unchanged, and a healthy post-cutover tick resets the budget
        handle.state = "ERROR"
        handle.retry_at_ms = 0.0

    def _revert_rescale(self, handle: QueryHandle, why: str) -> None:
        """A cutover failed before the new mesh could own the query:
        restore the previous shard override so the ladder's next rebuild
        comes back up at the PREVIOUS size, where the snapshot restores
        without resharding."""
        info = handle.pending_rescale
        if info is None:
            return
        handle.pending_rescale = None
        handle.shard_override = info.get("prev_override")
        if info.get("direction") in ("degrade", "regrow"):
            # a failed containment cutover: re-accrue strikes fresh at the
            # reverted width (the penalty below gates how soon the next
            # threshold crossing may re-pay the cutover cost)
            handle.shard_strikes = {}
        # escalate the cooldown multiplicatively: a refused reshard
        # (un-movable state) would otherwise re-pay the full cutover cost
        # (engine checkpoint + two recompiles + failed restore) every
        # plain cooldown period forever
        handle.rescale_penalty = min((handle.rescale_penalty or 1) * 2, 64)
        self._plog_append(
            f"rescale.revert:{handle.query_id}",
            f"{info.get('direction')} {info.get('from')}->"
            f"{info.get('target')} aborted ({why}); reverting to "
            f"{info.get('from')} shards",
        )
        if handle.progress is not None:
            handle.progress.note_event("rescale.revert",
                                       reason=str(why)[:200])

    def _note_poison_bisect(self, handle: QueryHandle, window: int) -> None:
        """A deterministic USER error hides somewhere in a batched flush of
        ``window`` replayable records: halve the records the next tick may
        poll.  Repeated deterministic re-crashes converge the window to one
        record in O(log window) restarts (each bounded by the normal retry
        ladder), at which point the crash IS attributable and the record is
        skipped atomically instead of crash-looping to terminal ERROR."""
        limit = max(1, int(window) // 2)
        handle.poison_bisect = {"limit": limit}
        self._plog_append(
            f"poison.bisect:{handle.query_id}",
            f"non-attributable poison in a batched flush of {window} "
            f"replayable records; next tick limited to {limit} records",
        )

    def _is_poison(self, e: Exception) -> bool:
        """True for deterministic USER-classified record errors: retrying
        them cannot succeed, so the record is skipped rather than the
        query crash-looped (ksql.fail.on.deserialization.error=false /
        LogAndContinueExceptionHandler analog).  Injected faults are never
        poison — they model transient infra failures and must take the
        restart+replay path regardless of what their message matches."""
        from ksql_tpu.common.faults import FaultInjected

        if isinstance(e, FaultInjected):
            return False
        etype = classify_error(
            e, str(self.effective_property("ksql.error.classifier.regex", ""))
        )
        return etype == "USER"

    # ----------------------------------------- error handling / self-healing
    def _query_failed(self, handle: QueryHandle, e: Exception) -> None:
        """Classify + enqueue the error, mark the query ERROR, and schedule
        a restart with exponential backoff (reference QueryMetadataImpl
        uncaught-exception handler + KsqlEngine restart path)."""
        import time as _time

        etype = classify_error(
            e, str(self.effective_property("ksql.error.classifier.regex", ""))
        )
        handle.error_queue.append(
            QueryError(int(_time.time() * 1000), f"{type(e).__name__}: {e}", etype)
        )
        max_q = int(self.effective_property("ksql.query.error.max.queue.size", 10))
        del handle.error_queue[:-max_q]
        self._on_error(f"query:{handle.query_id}:{etype}", e)
        self.metrics.for_query(handle.query_id).errors.mark(1)
        # post-mortem: the triggering tick's trace goes to the processing
        # log NOW (the ring also retains it, but a restart wipes executor
        # state — the log is the durable record of what the tick was
        # doing).  Only the ACTIVE tick is dumped: a failure outside any
        # tick (e.g. an executor rebuild in _maybe_restart) must not
        # relabel a retained earlier tick with an unrelated error.
        tr = tracing.active()
        if tr is not None and tr.query_id == handle.query_id:
            tr.status = "ERROR"
            tr.error = f"{type(e).__name__}: {e}"
            self._dump_trace(handle.query_id, tr)
        handle.state = "ERROR"
        retry_max = int(self.effective_property(cfg.QUERY_RETRY_MAX, 2147483647))
        if handle.restart_count >= retry_max:
            # restart budget exhausted: terminal ERROR — no more self-healing
            # attempts; /healthcheck flips unhealthy with this query id
            handle.terminal = True
            self._on_error(
                f"query:{handle.query_id}:terminal",
                KsqlException(
                    f"query {handle.query_id} exceeded {cfg.QUERY_RETRY_MAX}="
                    f"{retry_max} restarts; transitioning to terminal ERROR"
                ),
            )
            # a terminal PRIMARY must not strand its window-family members
            # (their emissions ride its device step): promote them to
            # standalone executors, same as TERMINATE does
            for m_qid in self._release_family(handle.query_id):
                mh = self.queries.get(m_qid)
                if mh is None or not mh.is_running():
                    continue
                try:
                    mh.executor = self._build_executor(mh)
                except Exception as me:  # noqa: BLE001 — promotion failure
                    self._query_failed(mh, me)  # takes the member's own ladder
            return
        initial = float(
            self.effective_property(cfg.QUERY_RETRY_BACKOFF_INITIAL_MS, 15000)
        )
        maximum = float(
            self.effective_property(cfg.QUERY_RETRY_BACKOFF_MAX_MS, 900000)
        )
        handle.retry_backoff_ms = min(
            (handle.retry_backoff_ms * 2) or initial, maximum
        )
        handle.retry_at_ms = _time.time() * 1000 + handle.retry_backoff_ms
        # mesh fault domain: a failure attributable to ONE shard of a
        # distributed mesh strikes that shard; past the threshold the
        # strike bookkeeping escalates to a degraded-mesh cutover (which
        # may zero the backoff above — the cutover IS the recovery)
        self._note_shard_strike(handle, e, etype)

    # ----------------------------------------- mesh fault domain (shards)
    def _note_shard_strike(self, handle: QueryHandle, e: Exception,
                           etype: str) -> None:
        """Shard-level failure containment: when a distributed query's
        failure names ONE shard — a classified-SYSTEM raise stamped with
        ``mesh_shard`` by the per-lane dispatch seam, or a tick deadline
        whose suspect-shard marker points at a wedged lane — the shard is
        marked suspect (``mesh.shard.suspect`` plog + /alerts evidence
        naming qid/shard/reason).  ``ksql.mesh.shard.fail.threshold``
        consecutive strikes (reset by any clean tick) trigger a
        degraded-mesh cutover instead of letting the single bad lane burn
        the whole query's retry ladder."""
        import time as _time

        if handle.backend != "distributed" or handle.terminal:
            return
        threshold = int(
            self.effective_property(cfg.MESH_FAIL_THRESHOLD, 3) or 0
        )
        if threshold <= 0:
            return
        shard = getattr(e, "mesh_shard", None)
        deadline = bool(getattr(e, "mesh_deadline", False))
        if shard is None or (etype != "SYSTEM" and not deadline):
            return  # not attributable to one shard: ordinary ladder
        shard = int(shard)
        strikes = handle.shard_strikes.get(shard, 0) + 1
        handle.shard_strikes[shard] = strikes
        handle.shard_strikes_total[shard] = (
            handle.shard_strikes_total.get(shard, 0) + 1
        )
        handle.last_shard_strike_ms = _time.time() * 1000
        reason = (
            f"tick deadline blown inside shard {shard}'s dispatch lane"
            if deadline else f"{type(e).__name__}: {e}"
        )
        self._plog_append(
            f"mesh.shard.suspect:{handle.query_id}",
            f"shard {shard} suspect ({strikes}/{threshold} consecutive "
            f"strikes): {reason}",
        )
        if handle.progress is not None:
            handle.progress.note_event(
                "mesh.shard.suspect", shard=shard, strikes=strikes,
                threshold=threshold, reason=str(reason)[:200],
            )
        if strikes >= threshold:
            self._degrade_mesh(handle, shard, reason, threshold)

    def _degrade_mesh(self, handle: QueryHandle, shard: int,
                      reason: str, threshold: int) -> None:
        """Execute the degraded-mesh cutover: rebuild the query at the
        next power of two BELOW its current width through the PR-9
        ``shard_override``/reshard-restore path, resuming from the last
        consistent checkpoint.  Runs from inside the failure path (the
        query is already ERROR with its offsets rewound to the commit
        point), so the engine checkpoint below carries each ERROR query's
        last CONSISTENT snapshot forward rather than snapshotting torn
        state.  A failed cutover reverts via ``rescale.revert`` exactly
        like a live rescale; un-movable state (ss-join ring buffers)
        refuses loudly in the reshard-restore.  ``mesh_degraded_from``
        remembers the original width for the regrow probe."""
        import time as _time

        if handle.pending_rescale is not None:
            return  # a cutover is already in flight
        cooldown = float(
            self.effective_property(cfg.RESCALE_COOLDOWN_MS, 60000)
        ) * max(1, handle.rescale_penalty)
        if (
            handle.rescale_penalty
            and _time.time() * 1000 - handle.last_rescale_ms < cooldown
        ):
            # a REVERTED cutover (un-movable state) must not re-pay the
            # checkpoint + two recompiles every threshold crossings: the
            # escalating penalty cooldown gates re-attempts, the plain
            # retry ladder keeps running meanwhile
            handle.shard_strikes[shard] = 0
            return
        cur = int(getattr(
            getattr(handle.executor, "device", None), "n_shards", 0
        ) or 0)
        if cur <= 1:
            # one shard IS the query: nothing to contain — plain ladder
            return
        target = 1 << ((cur - 1).bit_length() - 1)
        stateful = bool(getattr(handle.executor, "stateful", False))
        directory = self.effective_property(cfg.STATE_CHECKPOINT_DIR)
        if stateful and not directory:
            # exactly the rescale posture: stateful state only crosses
            # meshes through the checkpoint tier — refuse, loudly, and
            # leave the query to the ordinary retry ladder at full width
            self._plog_append(
                f"mesh.degrade.no-checkpoint:{handle.query_id}",
                f"cannot degrade {cur}->{target} shards around suspect "
                f"shard {shard}: stateful query and no "
                f"{cfg.STATE_CHECKPOINT_DIR}; set it to enable "
                "degraded-mesh cutovers",
            )
            handle.shard_strikes[shard] = 0
            return
        if directory:
            try:
                # the cutover's commit point: ERROR queries (this one)
                # carry their last consistent snapshot forward, healthy
                # siblings snapshot fresh (save_checkpoint contract)
                self.checkpoint()
            except Exception as e2:  # noqa: BLE001 — no snapshot, no
                self._on_error("mesh-degrade-checkpoint", e2)  # cutover
                handle.shard_strikes[shard] = 0
                return
        handle.pending_rescale = {
            "target": target, "from": cur, "direction": "degrade",
            "prev_override": handle.shard_override,
            "phases": {}, "suspect_shard": shard,
        }
        handle.shard_override = target
        handle.last_rescale_ms = _time.time() * 1000
        self._plog_append(
            f"mesh.degrade:{handle.query_id}",
            f"degraded-mesh cutover {cur}->{target} shards: shard {shard} "
            f"reached {cfg.MESH_FAIL_THRESHOLD}={threshold} consecutive "
            f"strikes ({reason}); rebuilding below the suspect width from "
            "the commit point",
        )
        if handle.progress is not None:
            handle.progress.note_event(
                "mesh.degrade", **{"from": cur, "to": target,
                                   "suspectShard": shard},
            )
        # the query is already ERROR (we run inside its failure path):
        # zero the backoff so the next poll iteration executes the cutover
        handle.retry_at_ms = 0.0

    def _maybe_mesh_regrow(self, handle: QueryHandle) -> None:
        """Regrow probe: once a degraded mesh has run strike-free for
        ``ksql.mesh.regrow.cooldown.ms`` (scaled by the revert penalty),
        cut back over to the query's original shard width.  If the fault
        has NOT cleared, the restored width strikes again and re-degrades
        — bounded by the same cooldown."""
        import time as _time

        if (
            handle.mesh_degraded_from is None
            or handle.state != "RUNNING"
            or handle.backend != "distributed"
            or handle.pending_rescale is not None
        ):
            return
        if self.overload.defer_elective():
            return  # regrow costs a compile: stay degraded until pressure clears
        cooldown = float(
            self.effective_property(cfg.MESH_REGROW_COOLDOWN_MS, 60000) or 0
        )
        if cooldown <= 0:
            return  # probe disabled: degraded until restart
        cooldown *= max(1, handle.rescale_penalty)
        quiet_since = max(handle.last_shard_strike_ms, handle.last_rescale_ms)
        if _time.time() * 1000 - quiet_since < cooldown:
            return
        target = int(handle.mesh_degraded_from)
        cur = int(getattr(
            getattr(handle.executor, "device", None), "n_shards", 0
        ) or 0)
        if not cur or target <= cur:
            handle.mesh_degraded_from = None  # already back at width
            return
        self._plog_append(
            f"mesh.regrow:{handle.query_id}",
            f"fault quiet for {int(cooldown)}ms: restoring the original "
            f"{target}-shard width ({cur}->{target} cutover)",
        )
        self._rescale_query(handle, target, "regrow")

    def _dump_trace(self, query_id: str, tr) -> None:
        """Write one tick trace (flight-recorder post-mortem) into the
        processing log — once per trace, however many times the error path
        re-touches it."""
        if getattr(tr, "_dumped", False):
            return
        import json as _json

        try:
            blob = _json.dumps(tr.to_dict(), separators=(",", ":"))
        except Exception:  # noqa: BLE001 — a trace must never break
            return  # the error path that is dumping it
        tr._dumped = True
        self._plog_append(f"trace:{query_id}", blob)
        if not self.is_sandbox:
            try:
                self._produce_processing_log(
                    f"trace:{query_id}", KsqlException(blob)
                )
            except Exception:  # noqa: BLE001 — the log must never recurse
                pass

    def _maybe_restart(self, handle: QueryHandle) -> None:
        """Self-healing restart once the backoff elapses: rebuild the
        executor fresh and restore its state from the last checkpoint (the
        reference restarts the streams runtime and restores every store
        from its changelog).  Terminal queries (retry budget exhausted)
        stay down.

        With ``ksql.query.rebuild.timeout.ms`` > 0 the rebuild+restore
        body runs on a supervised worker under the same zombie-fence
        discipline as tick supervision (the carried-forward ROADMAP gap:
        a hung XLA compile here used to block the WHOLE poll loop).  The
        fence is ``handle.rebuild_token`` identity: the deadline handler
        swaps it, every handle/engine mutation below is ``alive()``-
        guarded (machine-checked by graftlint's unfenced-handle-mutation
        rule), and ``_build_executor`` threads the same fence through its
        emit-fence swap and family registration."""
        import time as _time

        from ksql_tpu.common import faults

        if handle.terminal or _time.time() * 1000 < handle.retry_at_ms:
            return
        # pre-supervision bookkeeping: the worker does not exist yet, so
        # these two writes cannot race it
        handle.restart_count += 1  # graftlint: disable=unfenced-handle-mutation
        token = object()
        handle.rebuild_token = token  # graftlint: disable=unfenced-handle-mutation

        def alive() -> bool:
            return handle.rebuild_token is token

        def rebuild() -> None:
            # the whole rebuild+restore records as one tick on the query's
            # flight recorder, phase-split by cutover.* spans (rebuild /
            # restore here; a reshard-restore adds gather / repartition /
            # insert inside checkpoint._prepare_reshard) — /query-trace
            # shows where a slow restart or rescale cutover spent its time
            rec = self.recorder_if_enabled(handle.query_id)
            with tracing.tick(rec) as cutover_tick:
                self._rebuild_body(handle, alive, cutover_tick)

        timeout_ms = float(
            self.effective_property(cfg.QUERY_REBUILD_TIMEOUT_MS, 0) or 0
        )
        if timeout_ms <= 0:
            rebuild()
            return
        worker = threading.Thread(
            target=rebuild, daemon=True, name=f"rebuild-{handle.query_id}"
        )
        worker.start()
        worker.join(timeout_ms / 1000.0)
        if not worker.is_alive():
            return
        # the rebuild blew its deadline (a wedged compile): fence the
        # worker off and escalate through the retry ladder — sibling
        # queries resume polling immediately instead of hanging behind it.
        # The swap is the revocation itself, so it must run unconditionally
        handle.rebuild_token = None  # graftlint: disable=unfenced-handle-mutation
        handle.rebuild_deadlines += 1  # graftlint: disable=unfenced-handle-mutation
        if handle.progress is not None:
            # truthful evidence kind: /alerts must point the operator at
            # the REBUILD knob, not the (possibly disabled) tick knob
            handle.progress.note_tick_deadline(
                int(timeout_ms), kind="rebuild.deadline"
            )
        self._plog_append(
            f"rebuild.deadline:{handle.query_id}",
            f"executor rebuild exceeded {cfg.QUERY_REBUILD_TIMEOUT_MS}="
            f"{int(timeout_ms)}ms; worker abandoned, retry ladder "
            "escalates",
        )
        self._query_failed(handle, KsqlException(
            f"executor rebuild deadline exceeded "
            f"({cfg.QUERY_REBUILD_TIMEOUT_MS}={int(timeout_ms)}ms): "
            "worker abandoned, next retry after backoff"
        ))

    def _rebuild_body(self, handle: QueryHandle, alive, cutover_tick) -> None:
        """The rebuild+restore body of ``_maybe_restart`` (runs inline or
        on a supervised worker, under the rebuild-token fence ``alive``
        and a cutover-phase flight-recorder tick)."""
        from ksql_tpu.common import faults

        try:
            # chaos seam: `executor.rebuild@<qid>:hang` models the XLA
            # compile wedge the supervision exists for — INSIDE the
            # try, so a raise-mode fault is contained like any rebuild
            # failure (ladder + backoff), never a poll-loop abort or a
            # silently-dead worker with no backoff advance
            faults.fault_point("executor.rebuild", handle.query_id)
            with tracing.span("cutover.rebuild"):
                fresh = self._build_executor(handle, live=alive)
        except Exception as e:  # noqa: BLE001 — rebuild failed: back
            if alive():  # off more
                self._revert_rescale(handle, "rebuild failed")
                self._query_failed(handle, e)
            return
        if not alive():
            return  # fenced off mid-compile: discard the muted executor
        handle.executor = fresh
        # Rebuilding alone replays the rewound batch into EMPTY state —
        # an aggregation double-counts the prefix it had already
        # absorbed.  Restore preference: the in-memory commit-point
        # epoch (newest — taken per durable record this incident,
        # consumer already rewound to its exact offsets) wins over the
        # disk checkpoint (older, but state + offsets snapshotted
        # atomically, so it rewinds offsets to ITS point); neither
        # available degrades to the PR-1 posture (empty state + replay
        # from the rewound offsets, at-least-once).
        restored = False
        ep = handle.epoch
        ep_positions = ep.get("positions") if ep is not None else None
        with tracing.span("cutover.restore"):
            if (
                ep is not None and ep.get("state") is not None
                and ep.get("backend") == handle.backend
                and hasattr(fresh, "restore_state_epoch")
                # the epoch must match the replay point exactly — a stale
                # or zombie-raced epoch (state ahead of the rewound
                # offsets) would double-count the replayed records
                and (ep_positions is None
                     or ep_positions == dict(handle.consumer.positions))
            ):
                try:
                    fresh.restore_state_epoch(ep["state"])
                    if ep.get("materialized") is not None and alive():
                        handle.materialized.clear()
                        handle.materialized.update(ep["materialized"])
                    if ep.get("emit_seq") is not None and hasattr(
                        fresh, "sink_writer"
                    ):
                        # fresh SinkWriter would restart ordinals at 0;
                        # continue the sequence so changelog frames stay
                        # monotone across the self-heal
                        fresh.sink_writer.emit_seq = int(ep["emit_seq"])
                    restored = True
                except Exception as e:  # noqa: BLE001 — torn epoch: fall
                    self._on_error("epoch-restore", e)  # back
            directory = self.effective_property(cfg.STATE_CHECKPOINT_DIR)
            if not restored and directory and alive():
                from ksql_tpu.runtime.checkpoint import (
                    restore_query_checkpoint,
                )

                try:
                    if restore_query_checkpoint(
                        self, handle, str(directory), live=alive
                    ):
                        restored = True
                        if alive():
                            # the disk snapshot's offsets now define the
                            # replay point; the newer in-memory epoch no
                            # longer matches
                            handle.epoch = None
                except Exception as e:  # noqa: BLE001 — a torn/mismatched
                    # snapshot must not block recovery: fall back to the
                    # PR-1 posture (empty state + whole-batch replay,
                    # at-least-once)
                    self._on_error("checkpoint-restore", e)
                    if handle.pending_rescale is not None and alive():
                        # a refused/torn reshard-restore must not resume a
                        # stateful query cold: revert to the previous shard
                        # count and retry through the ladder — the next
                        # rebuild restores the same snapshot unresharded
                        self._revert_rescale(handle, f"restore failed: {e}")
                        self._query_failed(handle, KsqlException(
                            "rescale cutover aborted (reshard-restore "
                            f"failed): {e}"
                        ))
                        return
        if not restored and alive():
            stateful_fresh = bool(getattr(fresh, "stateful", False))
            if handle.pending_rescale is not None and stateful_fresh:
                # a CUTOVER (rescale or degraded-mesh) of a stateful
                # query found nothing to restore: resuming at the new
                # width would silently cold-start the aggregation —
                # revert to the previous shard count and retry through
                # the ladder (periodic checkpointing will produce a
                # restorable snapshot before the next attempt)
                self._revert_rescale(
                    handle, "no restorable epoch/checkpoint at cutover"
                )
                self._query_failed(handle, KsqlException(
                    "cutover aborted: stateful query with no restorable "
                    "state epoch or checkpoint"
                ))
                return
            # tier 3 of the recovery ladder: no epoch, no checkpoint
            # generation + changelog tail (tiers 1-2) — the query resumes
            # with EMPTY state and replays the rewound batch.  Delivery
            # stays at-least-once; for stateful queries the aggregate
            # state before the rewind point is GONE: say so loudly, in
            # the processing log AND the /alerts evidence ring
            self._plog_append(
                f"restart.no-checkpoint:{handle.query_id}",
                "recovery ladder exhausted: no state epoch, no intact "
                "checkpoint generation, no changelog tail "
                f"({cfg.STATE_CHECKPOINT_DIR}="
                f"{str(directory) or '<unset>'}): restarting with "
                "empty state + whole-batch replay (at-least-once; "
                "bounded replay needs a checkpoint dir"
                + ("; pre-rewind aggregate state is lost)"
                   if stateful_fresh else ")"),
            )
            if handle.progress is not None:
                handle.progress.note_event(
                    "restart.no-checkpoint",
                    checkpointDir=str(directory) or None,
                    stateful=stateful_fresh,
                )
        if alive():
            if handle.pending_rescale is not None:
                # cutover complete: the executor runs on the new mesh
                # and (stateful queries) the reshard-restore above
                # re-partitioned its state to the commit point
                info = handle.pending_rescale
                handle.pending_rescale = None
                direction = info.get("direction", "grow")
                handle.reshard_total[direction] = (
                    handle.reshard_total.get(direction, 0) + 1
                )
                handle.rescale_penalty = 0
                if direction == "degrade":
                    # running below the suspect width now: remember the
                    # ORIGINAL width (first degrade wins across repeated
                    # degrades) for the regrow probe, and give the new
                    # mesh a clean slate of strikes
                    if handle.mesh_degraded_from is None:
                        handle.mesh_degraded_from = (
                            int(info.get("from") or 0) or None
                        )
                    handle.shard_strikes = {}
                elif direction == "regrow":
                    # fault cleared and the original width restored
                    handle.mesh_degraded_from = None
                    handle.shard_strikes = {}
                # the initiation phases (drain + commit-point checkpoint,
                # stashed by _rescale_query) merge with this tick's
                # rebuild/restore/gather/repartition/insert spans: the
                # /alerts evidence names where the WHOLE cutover went
                phases = {
                    str(k): float(v)
                    for k, v in (info.get("phases") or {}).items()
                }
                if cutover_tick is not None:
                    for name, st in cutover_tick.stages.items():
                        if name.startswith("cutover."):
                            phases[name] = round(
                                phases.get(name, 0.0)
                                + float(st.get("ms", 0.0)), 3,
                            )
                self._plog_append(
                    f"rescale.done:{handle.query_id}",
                    f"{direction} cutover complete: "
                    f"{info.get('from')}->{info.get('target')} shards"
                    + (f"; phases(ms)={phases}" if phases else ""),
                )
                if handle.progress is not None:
                    handle.progress.note_event(
                        "rescale.done", direction=direction,
                        phasesMs=phases,
                        **{"from": info.get("from"),
                           "to": info.get("target")},
                    )
            handle.state = "RUNNING"
            # a completed rebuild/cutover is the moment a mis-sized
            # deadline becomes attributable: hint when a configured
            # tick/rebuild deadline sits below the observed cold-compile
            # p99 (the "kills every rebuilt tick" footgun, with evidence)
            self._deadline_hint(handle)

    def _deadline_hint(self, handle: QueryHandle) -> None:
        """Deadline auto-sizing: after a rebuild/cutover completes,
        compare the configured ``ksql.query.tick.timeout.ms`` /
        ``ksql.query.rebuild.timeout.ms`` against the cold-compile p99 the
        flight recorder actually observed for this query; a deadline sized
        below it would deadline-kill every rebuilt tick in a loop.

        Default posture (hint-only): log a ``deadline.hint`` plog entry
        and an /alerts evidence event naming the observed value.  With
        ``ksql.query.deadline.autosize`` on, go one step further and
        RAISE the undersized knob to observed p99 x
        ``ksql.query.deadline.autosize.margin`` (engine-wide session
        override — the same precedence a SET statement has), logging
        ``deadline.autosize`` with old->new.  Auto-sizing only ever
        raises: a generous deadline is never tightened."""
        rec = self.trace_recorders.get(handle.query_id)
        if rec is None:
            return
        st = rec.stage_stats().get("device.compile")
        p99 = st.get("p99_ms") if st else None
        if not p99:
            return
        autosize = cfg._bool(
            self.effective_property(cfg.DEADLINE_AUTOSIZE, False)
        )
        margin = float(
            self.effective_property(cfg.DEADLINE_AUTOSIZE_MARGIN, 2.0) or 2.0
        )
        for key in (cfg.QUERY_TICK_TIMEOUT_MS, cfg.QUERY_REBUILD_TIMEOUT_MS):
            configured = float(self.effective_property(key, 0) or 0)
            if not configured or configured >= p99:
                continue
            if autosize:
                raised = int(-(-float(p99) * max(margin, 1.0) // 1))
                self.session_properties[key] = raised
                self._plog_append(
                    f"deadline.autosize:{handle.query_id}",
                    f"{key} raised {int(configured)}ms -> {raised}ms: the "
                    f"configured deadline sat below the observed "
                    f"cold-compile p99 ({p99:.0f}ms) and would have "
                    "deadline-killed every rebuilt tick "
                    f"(ksql.query.deadline.autosize margin {margin:g}x)",
                )
                if handle.progress is not None:
                    handle.progress.note_event(
                        "deadline.autosize", knob=key,
                        oldMs=int(configured), newMs=raised,
                        observedColdCompileP99Ms=round(float(p99), 1),
                    )
                continue
            self._plog_append(
                f"deadline.hint:{handle.query_id}",
                f"{key}={int(configured)}ms is below the observed "
                f"cold-compile p99 ({p99:.0f}ms) for this query: a "
                "deadline sized under cold compile deadline-kills every "
                f"rebuilt tick — raise it above {p99:.0f}ms",
            )
            if handle.progress is not None:
                handle.progress.note_event(
                    "deadline.hint", knob=key,
                    configuredMs=int(configured),
                    observedColdCompileP99Ms=round(float(p99), 1),
                )

    def run_until_quiescent(self, max_iters: int = 1000) -> None:
        for _ in range(max_iters):
            if self.poll_once() == 0:
                return

    def flush_all_time(self, stream_time: int) -> None:
        """Advance event time across queries (closes windows; used by tests
        and the EMIT FINAL path)."""
        for handle in self.queries.values():
            if handle.is_running():
                handle.executor.flush_time(stream_time)
        self.run_until_quiescent()

    # ------------------------------------------------------- INSERT VALUES
    def _h_insert_values(self, s: ast.InsertValues, text):
        source = self.metastore.require_source(s.target)
        header_names = {n for n, _ in source.header_columns}
        if header_names and (
            not s.columns or any(c.upper() in header_names for c in s.columns)
        ):
            raise KsqlException(
                "Cannot insert into HEADER columns: "
                + ", ".join(sorted(header_names))
            )
        if source.is_source:
            raise KsqlException(
                f"Cannot insert values into read-only {'table' if source.is_table() else 'stream'}: "
                f"{s.target}"
            )
        schema = source.schema
        all_cols = list(schema.columns())
        if s.columns:
            cols = []
            for name in s.columns:
                c = schema.find_column(name)
                if c is None and name != "ROWTIME":
                    raise KsqlException(f"Column name {name} does not exist.")
                cols.append(c if c is not None else name)
        else:
            cols = all_cols
        if len(s.values) != len(cols):
            raise KsqlException(
                f"Expected a value for each column. Columns: {len(cols)}, "
                f"values: {len(s.values)}"
            )
        compiler = ExpressionCompiler(TypeResolver({}), self.registry)
        row: Dict[str, Any] = {}
        ts = None
        for c, vexpr in zip(cols, s.values):
            value = compiler.compile(vexpr)({})
            if c == "ROWTIME" or (not isinstance(c, str) and c.name == "ROWTIME"):
                ts = int(value)
                continue
            if value is not None:
                caster = make_caster(compiler.compile(vexpr).sql_type, c.type)
                value = caster(value)
            row[c.name] = value
        import time as _time

        if ts is None:
            ts = int(_time.time() * 1000)
        from ksql_tpu.serde import formats as fmt

        value_serde = fmt.of(
            source.value_format, wrap_single_values=source.wrap_single_values
        )
        key = tuple(row.get(c.name) for c in schema.key_columns)
        payload = value_serde.serialize(
            {c.name: row.get(c.name) for c in schema.value_columns},
            list(schema.value_columns),
        )
        self.broker.create_topic(source.topic)
        self.broker.topic(source.topic).produce(
            Record(key=fmt.serialize_key(source.key_format.format, key, schema.key_columns,
                                         wrapped=source.key_format.wrapped,
                                         delimiter=getattr(source, "key_delimiter", None)),
                   value=payload, timestamp=ts, partition=-1)
        )
        return StatementResult("ok", "Inserted")

    # ------------------------------------------------------------- queries
    def _h_query(self, q: ast.Query, text):
        """Transient query: push (EMIT CHANGES) or pull (no refinement)."""
        if q.refinement is not None and q.refinement.type == ast.RefinementType.CHANGES:
            return self._push_query(q, text)
        return self._pull_query(q, text)

    def _push_query(self, q: ast.Query, text) -> StatementResult:
        query_id = f"transient_{next(self._query_seq)}"
        analysis = analyze_query(q, self.metastore, self.registry)
        planned = self.planner.plan(analysis, query_id)
        rows: List[dict] = []
        limit = q.limit

        source_topics = sorted(
            {step.topic for step in st.walk_steps(planned.plan.physical_plan)
             if hasattr(step, "topic") and not isinstance(step, (st.StreamSink, st.TableSink))}
        )
        consumer = Consumer(self.broker, source_topics)
        out_schema = planned.plan.physical_plan.schema
        columns = [c.name for c in out_schema.key_columns] + [
            c.name for c in out_schema.value_columns
        ]

        def on_emit(e: SinkEmit):
            if limit is not None and len(rows) >= limit:
                return
            row = dict(zip([c.name for c in out_schema.key_columns], e.key))
            if e.row:
                row.update(e.row)
            if e.window is not None:
                row.setdefault("WINDOWSTART", e.window[0])
                row.setdefault("WINDOWEND", e.window[1])
            rows.append(row)

        # transient queries use the same backend seam as persistent ones:
        # device when the plan lowers, oracle otherwise (TransientQueryMetadata
        # runs on the shared runtime in the reference)
        executor = None
        backend = str(self.effective_property(cfg.RUNTIME_BACKEND)).lower()
        if backend != "oracle":
            from ksql_tpu.compiler.jax_expr import DeviceUnsupported
            from ksql_tpu.runtime.device_executor import DeviceExecutor

            device_plan = self._wrap_transient_plan(planned.plan, query_id)
            try:
                executor = DeviceExecutor(
                    device_plan, self.broker, self.registry,
                    on_error=self._on_error, emit_callback=on_emit,
                    batch_size=int(self.config.get(cfg.BATCH_CAPACITY)),
                    per_record=True,  # transient output order is per-record
                    store_capacity=int(self.config.get(cfg.STATE_SLOTS)),
                )
            except DeviceUnsupported:
                pass
            except Exception as e:  # noqa: BLE001
                if backend == "device-only":
                    raise
                self._on_error("device-lowering", e)
        if executor is None:
            self.annotate_serde_semantics(planned.plan)
            executor = OracleExecutor(
                planned.plan, self.broker, self.registry,
                on_error=self._on_error, emit_callback=on_emit,
            )
        # synchronous drain (server mode runs this on a thread)
        while True:
            records = consumer.poll()
            if not records:
                break
            for topic, rec in records:
                executor.process(topic, rec)
            drain = getattr(executor, "drain", None)
            if drain is not None:
                drain()
            if limit is not None and len(rows) >= limit:
                break
        return StatementResult("rows", query_id=query_id, rows=rows, columns=columns)

    @staticmethod
    def _pull_key_constraints(where, key_names, key_types):
        """LookupConstraint extraction (PullQueryRewriter/QueryFilterNode):
        when the WHERE clause pins EVERY key column with top-level
        conjunctive equality or IN constraints, return the list of exact
        key tuples to probe; else None (table scan).  The full WHERE still
        runs as a residual filter, so over-approximation is safe."""
        from ksql_tpu.execution import expressions as ex
        from ksql_tpu.serde.formats import _coerce

        if where is None or not key_names:
            return None

        def literal_value(e):
            if isinstance(e, (ex.IntegerLiteral, ex.LongLiteral,
                              ex.DoubleLiteral, ex.BooleanLiteral,
                              ex.StringLiteral)):
                return e.value
            if isinstance(e, ex.NullLiteral):
                return None  # WHERE key = NULL: probes nothing, matches nothing
            if isinstance(e, ex.DecimalLiteral):
                import decimal as _d

                return _d.Decimal(e.text)
            return _NO_LITERAL

        def conjuncts(e):
            if isinstance(e, ex.LogicalBinary) and e.op == ex.LogicOp.AND:
                return conjuncts(e.left) + conjuncts(e.right)
            return [e]

        values = {}  # key col name -> list of candidate values
        for c in conjuncts(where):
            col = vals = None
            if isinstance(c, ex.Comparison) and c.op == ex.CompareOp.EQ:
                for a, b in ((c.left, c.right), (c.right, c.left)):
                    v = literal_value(b)
                    if isinstance(a, ex.ColumnRef) and v is not _NO_LITERAL:
                        col, vals = a.name, [v]
                        break
            elif (isinstance(c, ex.InList) and not c.negated
                  and isinstance(c.value, ex.ColumnRef)):
                items = [literal_value(i) for i in c.items]
                if all(v is not _NO_LITERAL for v in items):
                    col, vals = c.value.name, items
            if col in key_names and col not in values:
                t = key_types[key_names.index(col)]
                try:
                    values[col] = [
                        _coerce(v, t) if v is not None else None for v in vals
                    ]
                except Exception:  # noqa: BLE001 — uncoercible: scan instead
                    return None
        if set(values) != set(key_names):
            return None
        import itertools as _it

        return [
            tuple(combo)
            for combo in _it.product(*(values[n] for n in key_names))
        ]

    def _pull_query(self, q: ast.Query, text) -> StatementResult:
        if not isinstance(q.from_, ast.Table):
            raise KsqlException("Pull queries only support a single source table")
        source_name = q.from_.name
        source = self.metastore.require_source(source_name)
        # find the query materializing this source
        handle = None
        for h in self.queries.values():
            if h.sink_name == source_name:
                handle = h
                break
        if source.is_table() and handle is None:
            raise KsqlException(
                f"Can't pull from {source_name} as it's not a materialized table."
            )
        if source.is_stream():
            raise KsqlException(
                "Pull queries on streams are not supported (use EMIT CHANGES)."
            )
        if not cfg._bool(self.effective_property("ksql.query.pull.enable", True)):
            raise KsqlException("Pull queries are disabled on this server.")
        # staleness gate (ksql.query.pull.max.allowed.offset.lag): a pull
        # against a badly lagging materialization is rejected rather than
        # served stale — standby reads accept the lag instead
        max_lag = int(
            self.effective_property(
                "ksql.query.pull.max.allowed.offset.lag", 9223372036854775807
            )
        )
        if max_lag < 9223372036854775807 and not cfg._bool(
            self.effective_property(cfg.STANDBY_READS, False)
        ):
            from ksql_tpu.common.metrics import consumer_lag

            lag = consumer_lag(handle.consumer)
            if lag > max_lag:
                raise KsqlException(
                    f"Failed to get value from materialized table: lag {lag} "
                    f"exceeds ksql.query.pull.max.allowed.offset.lag {max_lag}."
                )
        schema = source.schema
        types = {c.name: c.type for c in schema.columns()}
        from ksql_tpu.common.schema import WINDOW_BOUNDS

        for n, t in WINDOW_BOUNDS.items():
            types.setdefault(n, t)
        compiler = ExpressionCompiler(TypeResolver(types), self.registry, self._on_error)
        where = compiler.compile(q.where) if q.where is not None else None
        out_rows = []
        key_names = [c.name for c in schema.key_columns]
        # device-backed queries serve pulls from the HBM store itself
        # (KsMaterializedTableIQv2 analog); oracle-backed queries fall back
        # to the host-side materialization shadow
        dev = getattr(handle.executor, "device", None) if handle else None
        if dev is not None and getattr(dev, "store_layout", None) is not None:
            emits = None
            key_types = [c.type for c in schema.key_columns]
            key_tuples = self._pull_key_constraints(q.where, key_names, key_types)
            if key_tuples is not None:
                # keyed fast path: hash-matched slots only (the reference's
                # KeyedTableLookupOperator via LookupConstraint analysis);
                # the full WHERE still runs below as the residual filter
                emits = dev.lookup_store(key_tuples)
            if emits is None:
                emits = dev.scan_store()
            entries = sorted(
                ((e.row, e.window, e.key) for e in emits),
                key=lambda t: repr((t[2], t[1])),
            )
        else:
            entries = [
                (row, win, key)
                for (_hkey, _window), (row, win, key, _ts) in sorted(
                    handle.materialized.items(), key=lambda kv: repr(kv[0])
                )
            ]
        for row, win, key in entries:
            if row is None:
                continue
            full = dict(zip(key_names, key))
            full.update(row)
            if win is not None:
                full["WINDOWSTART"], full["WINDOWEND"] = win
            if where is not None and where(full) is not True:
                continue
            out_rows.append(full)
        # project
        from ksql_tpu.execution import expressions as ex

        star = any(isinstance(item, ast.AllColumns) for item in q.select.items)
        result_rows = []
        if star:
            columns = key_names + (
                ["WINDOWSTART", "WINDOWEND"] if source.key_format.windowed else []
            ) + schema.value_column_names()
            result_rows = [{c: r.get(c) for c in columns} for r in out_rows]
        else:
            sel = []
            columns = []
            for i, item in enumerate(q.select.items):
                expr = item.expression
                if isinstance(expr, ex.ColumnRef) and expr.source is not None:
                    expr = ex.ColumnRef(name=expr.name)
                alias = item.alias or (
                    expr.name if isinstance(expr, ex.ColumnRef) else f"KSQL_COL_{i}"
                )
                columns.append(alias)
                sel.append((alias, compiler.compile(expr)))
            for r in out_rows:
                result_rows.append({a: f(r) for a, f in sel})
        if q.limit is not None:
            result_rows = result_rows[: q.limit]
        return StatementResult("rows", rows=result_rows, columns=columns)

    # ---------------------------------------------------------------- admin
    def _h_drop(self, s: ast.DropSource, text):
        source = self.metastore.get_source(s.name)
        kind = "Table" if s.is_table else "Stream"
        if source is None:
            if s.if_exists:
                return StatementResult("ddl", f"Source {s.name} does not exist.")
            # DropSourceFactory: named by the statement's source kind
            raise KsqlException(f"{kind} {s.name} does not exist.")
        if s.delete_topic and source.is_source:
            raise KsqlException(
                f"Cannot delete topic for read-only source: {s.name}"
            )
        # downstream sources (sinks of queries reading this one) block the
        # drop; the query writing INTO this source terminates implicitly
        # (reference DropSourceFactory referential-integrity semantics)
        downstream = sorted({
            self.queries[qid].sink_name
            for qid in self.metastore.readers_of(s.name)
            if qid in self.queries and self.queries[qid].sink_name
        })
        if downstream:
            raise KsqlException(
                f"Cannot drop {s.name}.\n"
                "The following streams and/or tables read from this source: "
                f"[{', '.join(downstream)}].\n"
                f"You need to drop them before dropping {s.name}."
            )
        for qid in sorted(self.metastore.writers_of(s.name)):
            h = self.queries.pop(qid, None)
            if h is not None:
                h.state = "TERMINATED"
            self._stop_tick_worker(qid)
            self.metastore.remove_query_references(qid)
        self.metastore.delete_source(s.name, check_constraints=False)
        if s.delete_topic:
            self.broker.delete_topic(source.topic)
        return StatementResult("ddl", f"Source {s.name} (topic: {source.topic}) was dropped.")

    def _h_terminate(self, s: ast.TerminateQuery, text):
        ids = [s.query_id] if s.query_id else list(self.queries)
        promoted: List[str] = []
        for qid in ids:
            h = self.queries.get(qid)
            if h is None:
                if s.query_id:
                    raise KsqlException(f"Unknown queryId: {qid}")
                continue
            promoted.extend(self._release_family(qid))
            h.state = "TERMINATED"
            if h.backend == "device":
                self.device_query_count -= 1
            elif h.backend == "distributed":
                self.distributed_query_count -= 1
            self.metastore.remove_query_references(qid)
            self.metrics.remove_query(qid)
            self.trace_recorders.pop(qid, None)
            self._stop_tick_worker(qid)
            del self.queries[qid]
        # members of a terminated primary promote to standalone executors,
        # resuming from their own consumer position with fresh window state
        # (the PR-5 stateful-rebuild posture)
        for m_qid in promoted:
            mh = self.queries.get(m_qid)
            if mh is None or not mh.is_running():
                continue
            try:
                mh.executor = self._build_executor(mh)
            except Exception as e:  # noqa: BLE001 — promotion failure goes
                # through the normal self-healing ladder, not TERMINATE
                self._query_failed(mh, e)
        return StatementResult("ok", f"Terminated {', '.join(ids) if ids else 'nothing'}")

    def _h_pause(self, s: ast.PauseQuery, text):
        for qid in ([s.query_id] if s.query_id else list(self.queries)):
            h = self.queries.get(qid)
            if h is None:
                raise KsqlException(f"Unknown queryId: {qid}")
            h.state = "PAUSED"
        return StatementResult("ok", "Paused")

    def _h_resume(self, s: ast.ResumeQuery, text):
        for qid in ([s.query_id] if s.query_id else list(self.queries)):
            h = self.queries.get(qid)
            if h is None:
                raise KsqlException(f"Unknown queryId: {qid}")
            h.state = "RUNNING"
        return StatementResult("ok", "Resumed")

    def _h_list_streams(self, s, text):
        rows = [
            {"name": d.name, "topic": d.topic, "keyFormat": d.key_format.format,
             "valueFormat": d.value_format, "windowed": d.key_format.windowed}
            for d in self.metastore.all_sources() if d.is_stream()
        ]
        return StatementResult("rows", rows=rows, columns=["name", "topic", "keyFormat", "valueFormat", "windowed"])

    def _h_list_tables(self, s, text):
        rows = [
            {"name": d.name, "topic": d.topic, "keyFormat": d.key_format.format,
             "valueFormat": d.value_format, "windowed": d.key_format.windowed}
            for d in self.metastore.all_sources() if d.is_table()
        ]
        return StatementResult("rows", rows=rows, columns=["name", "topic", "keyFormat", "valueFormat", "windowed"])

    def _h_list_topics(self, s, text):
        rows = [{"name": t} for t in self.broker.list_topics()]
        return StatementResult("rows", rows=rows, columns=["name"])

    def _h_list_queries(self, s, text):
        rows = [
            {"id": h.query_id, "status": h.state, "sink": h.sink_name,
             "backend": h.backend, "health": h.health, "sql": h.sql}
            for h in self.queries.values()
        ]
        return StatementResult(
            "rows", rows=rows,
            columns=["id", "status", "sink", "backend", "health", "sql"],
        )

    def _h_list_properties(self, s, text):
        props = self.config.to_dict()
        props.update(self.session_properties)
        rows = [{"name": k, "value": str(v)} for k, v in sorted(props.items())]
        return StatementResult("rows", rows=rows, columns=["name", "value"])

    def _h_list_functions(self, s, text):
        rows = [{"name": n, "type": t} for n, t in self.registry.list_functions()]
        return StatementResult("rows", rows=rows, columns=["name", "type"])

    def _h_list_types(self, s, text):
        rows = [{"name": n, "schema": str(t)} for n, t in sorted(self.metastore.all_types().items())]
        return StatementResult("rows", rows=rows, columns=["name", "schema"])

    def _h_list_variables(self, s, text):
        rows = [{"name": k, "value": v} for k, v in sorted(self.variables.items())]
        return StatementResult("rows", rows=rows, columns=["name", "value"])

    def _h_show_columns(self, s: ast.ShowColumns, text):
        d = self.metastore.require_source(s.source)
        rows = []
        for c in d.schema.key_columns:
            rows.append({"column": c.name, "type": str(c.type), "key": "KEY"})
        for c in d.schema.value_columns:
            rows.append({"column": c.name, "type": str(c.type), "key": ""})
        message = ""
        if s.extended:
            # DESCRIBE EXTENDED reports the runtime executing the
            # materializing query (reference runtime-statistics section)
            for h in self.queries.values():
                if h.sink_name == d.name:
                    message = f"Runtime: {h.backend}"
                    shards = getattr(
                        getattr(h.executor, "device", None), "n_shards", None
                    )
                    if shards is not None:
                        message += f" (shards={shards})"
                    if h.progress is not None:
                        p = h.progress
                        message += (
                            f" · Health: {p.health} (lag={p.offset_lag}, "
                            f"watermark={p.watermark_ms}, "
                            f"e2e_p99_ms={p.e2e.percentile(0.99)})"
                        )
                    break
        return StatementResult(
            "rows", message, rows=rows, columns=["column", "type", "key"]
        )

    def _h_describe_function(self, s: ast.DescribeFunction, text):
        return StatementResult("ok", self.registry.describe(s.name))

    def _h_explain(self, s: ast.Explain, text):
        if s.query_id is not None:
            h = self.queries.get(s.query_id)
            if h is None:
                raise KsqlException(f"Query with id:{s.query_id} does not exist")
            if getattr(s, "analyze", False):
                return self._explain_analyze(h)
            # running queries report WHICH runtime executes the plan (the
            # reference's EXPLAIN shows the physical Streams topology)
            runtime = f"Runtime: {h.backend}"
            dev = getattr(h.executor, "device", None)
            shards = getattr(dev, "n_shards", None)
            if shards is not None:
                runtime += f" (shards={shards})"
            wline = self._windowing_line(h)
            if wline:
                runtime += "\n" + wline
            oline = self._optimizer_line(h)
            if oline:
                runtime += "\n" + oline
            # the ahead-of-time decision next to the live one: agreement is
            # the plan-verifier contract (tested over the golden corpus);
            # divergence means the runtime hit a non-plan failure (OOM,
            # compile error) classification cannot see
            try:
                static = self._classify_plan_static(h.plan, handle=h).format()
            except Exception as e:  # noqa: BLE001 — EXPLAIN must not fail
                static = f"Backend (static): unavailable ({e})"
            static += "\n" + self._memory_line(h.plan, handle=h)
            return StatementResult(
                "ok",
                runtime + "\n" + static + "\n"
                + st.format_plan(h.plan.physical_plan),
            )
        if getattr(s, "analyze", False):
            raise KsqlException(
                "EXPLAIN ANALYZE requires a running query id (it reports "
                "the flight recorder's per-stage measurements, not a plan)."
            )
        inner = s.statement
        if isinstance(inner, ast.Query):
            analysis = analyze_query(inner, self.metastore, self.registry)
            planned = self.planner.plan(analysis, "EXPLAIN")
            from ksql_tpu.analysis import verify_plan

            lines = []
            try:
                lines.append(
                    self._classify_transient_static(planned.plan).format()
                )
            except Exception as e:  # noqa: BLE001 — EXPLAIN must not fail
                lines.append(f"Backend (static): unavailable ({e})")
            lines.append(
                self._memory_line(
                    self._wrap_transient_plan(planned.plan, "explain")
                )
            )
            try:
                violations = verify_plan(planned.plan)
            except Exception as e:  # noqa: BLE001 — EXPLAIN must not fail
                violations = []
                lines.append(f"Plan verification unavailable ({e})")
            for v in violations:
                lines.append(f"Plan violation: {v.format()}")
            lines.append(st.format_plan(planned.plan.physical_plan))
            return StatementResult("ok", "\n".join(lines))
        raise KsqlException("EXPLAIN supports queries only")

    def _memory_line(self, plan, handle: Optional[QueryHandle] = None) -> str:
        """EXPLAIN's ``Device memory (static)`` component table: the
        memory model's per-component at-creation / at-growth-cap bytes
        (per shard), memoized on the handle for running queries.  Plans
        that never reach the device report n/a — they hold no HBM."""
        try:
            report = handle.mem_report if handle is not None else None
            if report is None:
                report = self._memory_report_static(plan)
                if handle is not None:
                    handle.mem_report = report
            if report is None:
                return (
                    "Device memory (static): n/a (plan does not run on "
                    "the device backend)"
                )
            return report.format_table()
        except Exception as e:  # noqa: BLE001 — EXPLAIN must not fail
            return f"Device memory (static): unavailable ({e})"

    def _windowing_line(self, h: QueryHandle) -> Optional[str]:
        """The live windowing shape of a running hopping aggregation:
        sliced (with slice width / ring / hop fan-out and any family
        members sharing the pipeline) or expansion (with the reason it
        could not slice)."""
        from ksql_tpu.runtime.device_executor import FamilyMemberExecutor

        ex_ = h.executor
        if isinstance(ex_, FamilyMemberExecutor):
            prim = self.queries.get(ex_.primary_query_id)
            dev = getattr(getattr(prim, "executor", None), "device", None)
            if dev is None or not getattr(dev, "sliced", False):
                return None  # source-prefix member: no windowing to report
            return (
                f"Windowing: sliced (width={dev.slice_width}ms, "
                f"shared with {ex_.primary_query_id})"
            )
        dev = getattr(ex_, "device", None)
        if dev is None:
            return None
        if getattr(dev, "sliced", False):
            line = (
                f"Windowing: sliced (width={dev.slice_width}ms, "
                f"ring={dev.slice_ring}, k={dev.hop_k}"
            )
            shared = dev.shared_member_ids()
            if shared:
                line += f", shared with {', '.join(sorted(shared))}"
            return line + ")"
        wf = getattr(dev, "windowing_fallback", None)
        if wf:
            return (
                f"Windowing: expansion (k={getattr(dev, 'hop_k', 1)}): {wf}"
            )
        return None

    def _optimizer_line(self, h: QueryHandle) -> Optional[str]:
        """EXPLAIN's ``Optimizer`` section: the multi-query optimizer's
        cost decision for this query plus — when it shares a pipeline —
        the shared-plan DAG (source -> shared stage -> every member's
        combine/residual -> sink), rendered identically whether EXPLAIN
        targets the primary or a member."""
        from ksql_tpu.runtime.device_executor import FamilyMemberExecutor

        dec = getattr(h, "mqo_decision", None)
        ex_ = h.executor
        lines: List[str] = []
        if isinstance(ex_, FamilyMemberExecutor):
            prim_qid = ex_.primary_query_id
            prim = self.queries.get(prim_qid)
            dev = getattr(getattr(prim, "executor", None), "device", None)
            kind = (
                "window-family" if getattr(dev, "sliced", False)
                else "source-prefix"
            )
            lines.append(
                f"Optimizer: member of shared {kind} pipeline "
                f"(primary={prim_qid})"
            )
            if dec is not None:
                lines.append("  " + dec.format())
            if dev is not None:
                lines.extend(self._shared_dag_lines(prim_qid, dev))
        else:
            dev = getattr(ex_, "device", None)
            members = []
            if dev is not None:
                members = list(getattr(dev, "shared_member_ids", list)())
                members += list(
                    getattr(dev, "shared_prefix_member_ids", list)()
                )
            if members:
                lines.append(
                    f"Optimizer: shared-pipeline primary "
                    f"({1 + len(members)} queries share this pipeline)"
                )
                lines.extend(self._shared_dag_lines(h.query_id, dev))
            elif dec is not None and not dec.share:
                lines.append("Optimizer: " + dec.format())
        return "\n".join(lines) if lines else None

    def _shared_dag_lines(self, prim_qid: str, dev) -> List[str]:
        """The shared-plan DAG EXPLAIN prints under ``Optimizer``."""
        out: List[str] = []
        topic = getattr(getattr(dev, "source", None), "topic", "?")
        if getattr(dev, "sliced", False):
            out.append(
                f"  shared DAG: source {topic} -> scan/filter/project -> "
                f"slice-ring[width={dev.slice_width}ms "
                f"ring={dev.slice_ring} "
                f"partials={len(dev.agg_specs)}]"
            )
            for m in dev.members:
                qid = m.query_id or prim_qid
                n_aggs = len(
                    m.agg_map if m.agg_map is not None else dev.agg_specs
                )
                out.append(
                    f"    -> combine[size={m.size_ms}ms "
                    f"advance={m.advance_ms}ms aggs={n_aggs}] -> {qid}"
                )
        else:
            shared_n = getattr(dev, "_prefix_shared_len", 0)
            out.append(
                f"  shared DAG: source {topic} -> shared "
                f"prefix[{shared_n} op(s)]"
            )
            out.append(
                f"    -> residual[{len(dev.pre_ops) - shared_n} op(s)] "
                f"-> {prim_qid}"
            )
            for m in dev.prefix_members:
                out.append(
                    f"    -> residual[{len(m.pre_ops) - shared_n} op(s)] "
                    f"-> {m.query_id}"
                )
        return out

    def _explain_analyze(self, h: QueryHandle) -> StatementResult:
        """EXPLAIN ANALYZE <query_id>: the flight recorder's per-stage
        p50/p99 breakdown over the ring window — poll/deserialize/
        per-ExecutionStep stages, the device compile-vs-execute split (with
        jit hit/miss counts), host<->device transfer bytes, distributed
        exchange rows/bytes, and sink produce."""
        import json as _json

        rec = self.trace_recorders.get(h.query_id)
        stats = rec.stage_stats() if rec is not None else {}
        runtime = f"Runtime: {h.backend}"
        dev = getattr(h.executor, "device", None)
        shards = getattr(dev, "n_shards", None)
        if shards is not None:
            runtime += f" (shards={shards})"
        window = rec.window_ticks() if rec is not None else 0
        msg = f"{runtime} · flight recorder window: {window} ticks"
        if not self.trace_enabled:
            msg += " · tracing disabled (ksql.trace.enable=false)"
        rows = []
        for name in sorted(stats, key=tracing.stage_sort_key):
            st_ = stats[name]
            extra = {
                k: v for k, v in st_.items()
                if k not in ("n", "ticks", "p50_ms", "p99_ms", "total_ms")
            }
            rows.append({
                "stage": name,
                "count": st_["n"],
                "p50Ms": st_["p50_ms"],
                "p99Ms": st_["p99_ms"],
                "totalMs": st_["total_ms"],
                "extra": _json.dumps(extra, sort_keys=True) if extra else "",
            })
        return StatementResult(
            "rows", msg, rows=rows,
            columns=["stage", "count", "p50Ms", "p99Ms", "totalMs", "extra"],
        )

    def _h_set(self, s: ast.SetProperty, text):
        self.session_properties[s.name] = s.value
        return StatementResult("ok", f"Property {s.name} set to {s.value}")

    def _h_unset(self, s: ast.UnsetProperty, text):
        self.session_properties.pop(s.name, None)
        return StatementResult("ok", f"Property {s.name} unset")

    def _h_define(self, s: ast.DefineVariable, text):
        self.variables[s.name] = s.value
        return StatementResult("ok", f"Variable {s.name} defined")

    def _h_undefine(self, s: ast.UndefineVariable, text):
        self.variables.pop(s.name, None)
        return StatementResult("ok", f"Variable {s.name} undefined")

    def _h_alter_system(self, s: ast.AlterSystemProperty, text):
        """ALTER SYSTEM 'prop'='value': mutate the server-level default
        (KsqlResource's ALTER SYSTEM path via KsqlConfig; session SET still
        overrides it).  Only recognized ksql.* keys are alterable."""
        from ksql_tpu.common.config import _DEFS

        if s.name not in _DEFS:
            raise KsqlException(
                f"Unknown property: '{s.name}'. ALTER SYSTEM accepts only "
                "known ksql server properties."
            )
        self.config._props[s.name] = self.config._coerce(s.name, s.value)
        return StatementResult("ok", f"System property {s.name} set to {s.value}")

    def _h_alter_source(self, s: ast.AlterSource, text):
        """ALTER STREAM|TABLE ... ADD COLUMN: append value columns to the
        registered schema (AlterSourceFactory.java:45 validations +
        DdlCommandExec.executeAlterSource semantics).  Running queries keep
        the schema they planned against."""
        kind = "TABLE" if s.is_table else "STREAM"
        source = self.metastore.get_source(s.name)
        if source is not None and source.is_source:
            raise KsqlException(
                f"Cannot alter {kind.lower()} '{s.name}': ALTER operations "
                f"are not supported on source {kind.lower()}s."
            )
        if source is None:
            raise KsqlException(f"Source {s.name} does not exist.")
        if source.source_type != kind:
            raise KsqlException(
                f"Incompatible data source type is {source.source_type}, "
                f"but statement was ALTER {kind}"
            )
        if source.is_cas_target:
            raise KsqlException(
                "ALTER command is not supported for CREATE ... AS statements."
            )
        b = LogicalSchema.builder()
        for c in source.schema.key_columns:
            b.key_column(c.name, c.type)
        existing = {c.name for c in source.schema.columns()}
        for c in source.schema.value_columns:
            b.value_column(c.name, c.type)
        for el in s.new_columns:
            if el.name in existing:
                raise KsqlException(
                    f"Cannot add column `{el.name}` to schema. A column with "
                    "the same name already exists."
                )
            existing.add(el.name)
            b.value_column(el.name, el.type)
        self.metastore.put_source(
            dataclasses.replace(
                source, schema=b.build(),
                sql_expression=(source.sql_expression + "\n" + text).strip(),
            ),
            allow_replace=True,
        )
        return StatementResult("ddl", f"{kind} {s.name} altered.")

    # ----------------------------------------------------------- connectors
    @property
    def _connect_client(self):
        from ksql_tpu.services.connect import ConnectClient, client_for

        url = str(self.config.get("ksql.connect.url") or "")
        cached = self.__dict__.get("_connect_client_cached")
        if cached is None or cached[0] != url:
            # sandbox validation must not touch a real Connect cluster
            # (Sandboxed* service mirror): validate-only in-process client.
            # keyed by url so ALTER SYSTEM 'ksql.connect.url' takes effect
            c = ConnectClient() if self.is_sandbox else client_for(self.config)
            cached = self.__dict__["_connect_client_cached"] = (url, c)
        return cached[1]

    def _h_create_connector(self, s: ast.CreateConnector, text):
        """CREATE SOURCE|SINK CONNECTOR (ConnectExecutor.java:48): validate
        config, register through the Connect seam, record in the metastore
        registry for LIST/DESCRIBE/DROP."""
        from ksql_tpu.metastore.metastore import ConnectorInfo

        name = s.name
        if self.metastore.get_connector(name) is not None:
            if s.if_not_exists:
                return StatementResult(
                    "ok", f"Connector {name} already exists"
                )
            raise KsqlException(f"Connector {name} already exists")
        props = {str(k): str(v) for k, v in (s.properties or {}).items()}
        self._connect_client.create(name, props)
        self.metastore.put_connector(ConnectorInfo(
            name=name,
            connector_type=s.connector_type.upper(),
            properties=tuple(sorted(props.items())),
        ))
        return StatementResult("ok", f"Created connector {name}")

    def _h_drop_connector(self, s: ast.DropConnector, text):
        if self.metastore.get_connector(s.name) is None:
            if s.if_exists:
                return StatementResult("ok", f"Connector {s.name} does not exist.")
            raise KsqlException(f"Connector {s.name} does not exist.")
        self._connect_client.delete(s.name)
        self.metastore.drop_connector(s.name)
        return StatementResult("ok", f"Dropped connector {s.name}")

    def _h_list_connectors(self, s: ast.ListConnectors, text):
        rows = [
            {
                "name": c.name,
                "type": c.connector_type,
                "className": c.connector_class,
                "state": self._connect_client.status(c.name),
            }
            for c in self.metastore.list_connectors()
            if s.scope in ("ALL", c.connector_type)
        ]
        return StatementResult(
            "rows", rows=rows, columns=["name", "type", "className", "state"]
        )

    def _h_describe_connector(self, s: ast.DescribeConnector, text):
        c = self.metastore.get_connector(s.name)
        if c is None:
            raise KsqlException(f"Connector {s.name} does not exist.")
        rows = [{
            "name": c.name,
            "type": c.connector_type,
            "className": c.connector_class,
            "state": self._connect_client.status(c.name),
            "properties": dict(c.properties),
        }]
        return StatementResult(
            "rows", rows=rows,
            columns=["name", "type", "className", "state", "properties"],
        )

    def _h_register_type(self, s: ast.RegisterType, text):
        created = self.metastore.register_type(s.name, s.type, s.if_not_exists)
        return StatementResult("ddl", "Type registered" if created else "Type already exists")

    def _h_drop_type(self, s: ast.DropType, text):
        self.metastore.drop_type(s.name, s.if_exists)
        return StatementResult("ddl", "Type dropped")

    def _h_print(self, s: ast.PrintTopic, text):
        topic = self.broker.topic(s.topic)
        records = topic.all_records()
        if s.limit is not None:
            records = records[: s.limit]
        rows = [
            {"partition": r.partition, "offset": r.offset, "timestamp": r.timestamp,
             "key": r.key, "value": r.value}
            for r in records
        ]
        return StatementResult("rows", rows=rows,
                               columns=["partition", "offset", "timestamp", "key", "value"])

    _HANDLERS: Dict[type, Callable] = {}


KsqlEngine._MUTATING = (
    ast.CreateStream,
    ast.CreateTable,
    ast.CreateStreamAsSelect,
    ast.CreateTableAsSelect,
    ast.InsertInto,
    ast.InsertValues,
    ast.DropSource,
    ast.RegisterType,
    ast.DropType,
    ast.AlterSource,
    ast.CreateConnector,
    ast.DropConnector,
)

KsqlEngine._HANDLERS = {
    ast.CreateStream: KsqlEngine._h_create_stream,
    ast.CreateTable: KsqlEngine._h_create_table,
    ast.CreateStreamAsSelect: KsqlEngine._h_csas,
    ast.CreateTableAsSelect: KsqlEngine._h_ctas,
    ast.InsertInto: KsqlEngine._h_insert_into,
    ast.InsertValues: KsqlEngine._h_insert_values,
    ast.Query: KsqlEngine._h_query,
    ast.DropSource: KsqlEngine._h_drop,
    ast.TerminateQuery: KsqlEngine._h_terminate,
    ast.PauseQuery: KsqlEngine._h_pause,
    ast.ResumeQuery: KsqlEngine._h_resume,
    ast.ListStreams: KsqlEngine._h_list_streams,
    ast.ListTables: KsqlEngine._h_list_tables,
    ast.ListTopics: KsqlEngine._h_list_topics,
    ast.ListQueries: KsqlEngine._h_list_queries,
    ast.ListProperties: KsqlEngine._h_list_properties,
    ast.ListFunctions: KsqlEngine._h_list_functions,
    ast.ListTypes: KsqlEngine._h_list_types,
    ast.ListVariables: KsqlEngine._h_list_variables,
    ast.ShowColumns: KsqlEngine._h_show_columns,
    ast.DescribeFunction: KsqlEngine._h_describe_function,
    ast.Explain: KsqlEngine._h_explain,
    ast.SetProperty: KsqlEngine._h_set,
    ast.UnsetProperty: KsqlEngine._h_unset,
    ast.DefineVariable: KsqlEngine._h_define,
    ast.UndefineVariable: KsqlEngine._h_undefine,
    ast.RegisterType: KsqlEngine._h_register_type,
    ast.DropType: KsqlEngine._h_drop_type,
    ast.PrintTopic: KsqlEngine._h_print,
    ast.AlterSource: KsqlEngine._h_alter_source,
    ast.AlterSystemProperty: KsqlEngine._h_alter_system,
    ast.CreateConnector: KsqlEngine._h_create_connector,
    ast.DropConnector: KsqlEngine._h_drop_connector,
    ast.ListConnectors: KsqlEngine._h_list_connectors,
    ast.DescribeConnector: KsqlEngine._h_describe_connector,
}
