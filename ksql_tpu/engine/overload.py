"""Overload manager: resource-pressure monitors driving prioritized
graceful degradation (the Envoy overload-manager analog, ISSUE 16).

The stack's only pressure responses so far are hard refusals (the
graftmem admission gate, ``_grow`` refusal) — nothing *sheds* load while
keeping the process healthy.  This module closes that gap with Envoy's
shape: scaled resource monitors -> per-resource OK/ELEVATED/CRITICAL
levels with release hysteresis -> a prioritized action ladder engaged
loudest-first and released in reverse.

Monitored resources (each folded to a pressure scalar, then a level):

``hbm``       live device HBM: sum of ``device_state_bytes()`` across
              device-backed queries vs ``ksql.analysis.memory.budget.bytes``
              (the PR-13 graftmem seam).  No budget -> pressure 0.
``inflight``  concurrent streaming REST responses vs
              ``ksql.overload.max.inflight`` (the server registers the
              gauge via :meth:`set_inflight_source`).
``lag``       max per-query consumer lag (health.py ``QueryProgress``)
              plus tick/rebuild-deadline pressure — deadlines blown
              within one monitor interval are direct evidence the engine
              cannot hold its tick budget.
``push``      push-registry ring occupancy and laggiest-tap lag, each as
              a fraction of the pipeline ring size (``stats()`` seam).

Action ladder, in ENGAGE order (the loudest / least-harmful-to-existing-
work actions first; release walks the same list in reverse):

1. ``admission``      (ELEVATED)  new transient pull/push queries get
                      429 + Retry-After at REST; persistent DDL via
                      /ksql stays accepted.
2. ``tap-clamp``      (ELEVATED)  push-tap poll sizes shrink to
                      ``ksql.overload.tap.poll.rows``; taps lagging past
                      ``ksql.overload.tap.lag.bound`` are disconnected
                      with a terminal gap marker naming overload —
                      never silently stalled.
3. ``source-pacing``  (CRITICAL)  per-query poll-size clamp ordered by
                      ``ksql.query.priority`` — device work is shed from
                      low-priority queries first while every sink stays
                      live.
4. ``defer-elective`` (CRITICAL)  rescale / mesh-regrow / MQO attach
                      attempts (each costs compiles) gate off.

Every engage/clear lands an ``overload.engage:<action>`` /
``overload.clear:<action>`` plog entry plus an /alerts evidence event;
``ksql_overload_state{resource}`` gauges and
``ksql_overload_actions_total{action}`` counters ride /metrics (JSON and
Prometheus).  ``chaos_soak.py --overload`` proves the ladder live.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ksql_tpu.common import config as cfg
from ksql_tpu.common import faults

OK = "OK"
ELEVATED = "ELEVATED"
CRITICAL = "CRITICAL"

#: numeric encoding for the ksql_overload_state gauge
LEVEL_NUM = {OK: 0, ELEVATED: 1, CRITICAL: 2}

#: the action ladder in ENGAGE order with the level that arms each rung;
#: release walks this list in reverse
ACTIONS = (
    ("admission", ELEVATED),
    ("tap-clamp", ELEVATED),
    ("source-pacing", CRITICAL),
    ("defer-elective", CRITICAL),
)

RESOURCES = ("hbm", "inflight", "lag", "push")


class OverloadManager:
    """Samples resource pressure and drives the degradation ladder.

    Owned by the engine (created in ``KsqlEngine.__init__``, cheap: no
    thread).  Sampling runs two ways: piggybacked on ``poll_once`` (every
    embedded engine gets protection for free) and, in server mode, on a
    dedicated monitor thread started by :meth:`start_monitor` so pressure
    is still observed while a poll tick is wedged.  Both paths funnel
    through :meth:`maybe_sample`, which is interval-gated and serialized
    by the manager's own lock."""

    def __init__(self, engine) -> None:
        self.engine = engine
        self._lock = threading.RLock()
        self._last_sample_ms = 0.0
        # per-resource current level + consecutive below-level streak
        # (the release hysteresis counter)
        self.levels: Dict[str, str] = {r: OK for r in RESOURCES}
        self._release_streak: Dict[str, int] = {r: 0 for r in RESOURCES}
        self.pressure: Dict[str, float] = {r: 0.0 for r in RESOURCES}
        self.engaged: Dict[str, bool] = {a: False for a, _ in ACTIONS}
        self.actions_total: Dict[str, int] = {a: 0 for a, _ in ACTIONS}
        self.shed_requests = 0
        self.taps_disconnected = 0
        self.samples = 0
        self.monitor_errors = 0
        #: /alerts evidence ring: every engage/clear lands here with the
        #: pressure snapshot that drove it
        self.events: collections.deque = collections.deque(maxlen=32)
        self._inflight_source: Optional[Callable[[], int]] = None
        self._deadline_base = 0  # deadlines seen as of the last sample
        self._monitor_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- config
    def _prop(self, key: str, default):
        return self.engine.effective_property(key, default)

    def enabled(self) -> bool:
        return cfg._bool(self._prop(cfg.OVERLOAD_ENABLE, True))

    # ----------------------------------------------------------- plumbing
    def set_inflight_source(self, fn: Callable[[], int]) -> None:
        """Server registration: a callable returning the live count of
        concurrent streaming REST responses."""
        with self._lock:
            self._inflight_source = fn

    @staticmethod
    def _now_ms() -> float:
        return time.time() * 1000

    # ----------------------------------------------------------- sampling
    def maybe_sample(self) -> bool:
        """Interval-gated sample; returns True when a sample ran.  Safe
        from any thread; never raises (a failing monitor must not take
        the poll loop down with it)."""
        if not self.enabled():
            return False
        interval = int(self._prop(cfg.OVERLOAD_INTERVAL_MS, 1000))
        with self._lock:
            now = self._now_ms()
            if now - self._last_sample_ms < interval:
                return False
            self._last_sample_ms = now
            self.samples += 1
            level = self._overall_level()
        try:
            # the chaos seam sits OUTSIDE the lock: a hang-mode rule
            # stalls only this sampler — the lock-free action seams and
            # the REST threads contending for note_shed()/stats() keep
            # moving
            faults.fault_point("overload.monitor", level)
            self._sample()
        except faults.FaultInjected as e:
            # an injected monitor failure is absorbed loudly — sampling
            # resumes next interval
            with self._lock:
                self.monitor_errors += 1
            self.engine._plog_append("overload.monitor", str(e))
        except Exception as e:  # noqa: BLE001 — monitor must survive
            with self._lock:
                self.monitor_errors += 1
            self.engine._on_error("overload.monitor", e)
        return True

    def _sample(self) -> None:
        with self._lock:
            pressures = {
                "hbm": self._hbm_pressure(),
                "inflight": self._inflight_pressure(),
                "lag": self._lag_pressure(),
                "push": self._push_pressure(),
            }
            hysteresis = int(self._prop(cfg.OVERLOAD_HYSTERESIS_TICKS, 3))
            for res, (pressure, level) in pressures.items():
                self.pressure[res] = pressure
                self._fold_level(res, level, hysteresis)
            self._apply_actions()

    def _fold_level(self, res: str, target: str, hysteresis: int) -> None:
        """Raises are immediate; a drop needs ``hysteresis`` consecutive
        samples at (or below) the lower level."""
        with self._lock:
            cur = self.levels[res]
            if LEVEL_NUM[target] >= LEVEL_NUM[cur]:
                self.levels[res] = target
                self._release_streak[res] = 0
                return
            self._release_streak[res] += 1
            if self._release_streak[res] >= max(1, hysteresis):
                # step DOWN one level at a time: CRITICAL releases through
                # ELEVATED, so actions disengage in reverse, not all at
                # once
                self.levels[res] = (
                    ELEVATED if cur == CRITICAL and target == OK else target
                )
                self._release_streak[res] = 0

    def _overall_level(self) -> str:
        worst = max(self.levels.values(), key=lambda lv: LEVEL_NUM[lv])
        return worst

    # -------------------------------------------------- resource monitors
    def _hbm_pressure(self):
        budget = int(self._prop(cfg.MEMORY_BUDGET_BYTES, 0) or 0)
        if budget <= 0:
            return 0.0, OK
        used = 0
        for h in list(self.engine.queries.values()):
            dev = getattr(getattr(h, "executor", None), "device", None)
            fn = getattr(dev, "device_state_bytes", None)
            if fn is None or not h.is_running():
                continue
            try:
                used += sum(int(v) for v in fn().values())
            except Exception:  # noqa: BLE001 — a mid-rebuild executor may
                continue  # have no live state; skip, don't kill the sample
        pressure = used / float(budget)
        elevated = float(self._prop(cfg.OVERLOAD_HBM_ELEVATED, 0.85))
        critical = float(self._prop(cfg.OVERLOAD_HBM_CRITICAL, 0.95))
        return pressure, self._bucket(pressure, elevated, critical)

    def _inflight_pressure(self):
        if self._inflight_source is None:
            return 0.0, OK
        try:
            inflight = int(self._inflight_source())
        except Exception:  # noqa: BLE001
            return 0.0, OK
        bound = max(1, int(self._prop(cfg.OVERLOAD_MAX_INFLIGHT, 64)))
        pressure = inflight / float(bound)
        elevated = float(self._prop(cfg.OVERLOAD_INFLIGHT_ELEVATED, 0.75))
        return pressure, self._bucket(pressure, elevated, 1.0)

    def _lag_pressure(self):
        elevated = max(1, int(self._prop(cfg.OVERLOAD_LAG_ELEVATED_ROWS,
                                         50000)))
        critical = max(1, int(self._prop(cfg.OVERLOAD_LAG_CRITICAL_ROWS,
                                         200000)))
        max_lag = 0
        deadlines = 0
        for h in list(self.engine.queries.values()):
            prog = getattr(h, "progress", None)
            if prog is not None:
                max_lag = max(max_lag, int(prog.offset_lag or 0))
            deadlines += int(getattr(h, "tick_deadlines", 0))
            deadlines += int(getattr(h, "rebuild_deadlines", 0))
        pressure = max_lag / float(critical)
        level = OK
        if max_lag >= critical:
            level = CRITICAL
        elif max_lag >= elevated:
            level = ELEVATED
        # deadline pressure: kills within ONE monitor interval
        new_deadlines = max(0, deadlines - self._deadline_base)
        self._deadline_base = deadlines
        dl_critical = max(1, int(self._prop(cfg.OVERLOAD_DEADLINE_CRITICAL,
                                            2)))
        if new_deadlines >= dl_critical:
            level = CRITICAL
            pressure = max(pressure, 1.0)
        elif new_deadlines >= 1 and level == OK:
            level = ELEVATED
            pressure = max(pressure, elevated / float(critical))
        return pressure, level

    def _push_pressure(self):
        registry = getattr(self.engine, "push_registry", None)
        if registry is None:
            return 0.0, OK
        pressure = 0.0
        try:
            pressure = float(registry.pressure())
        except Exception:  # noqa: BLE001 — a torn-down registry reads idle
            return 0.0, OK
        elevated = float(self._prop(cfg.OVERLOAD_RING_ELEVATED, 0.7))
        critical = float(self._prop(cfg.OVERLOAD_RING_CRITICAL, 0.95))
        return pressure, self._bucket(pressure, elevated, critical)

    @staticmethod
    def _bucket(pressure: float, elevated: float, critical: float) -> str:
        if pressure >= critical:
            return CRITICAL
        if pressure >= elevated:
            return ELEVATED
        return OK

    # ----------------------------------------------------- action ladder
    def _apply_actions(self) -> None:
        with self._lock:
            overall = self._overall_level()
            # engage loudest-first (ladder order)...
            for action, arm_level in ACTIONS:
                want = LEVEL_NUM[overall] >= LEVEL_NUM[arm_level]
                if want and not self.engaged[action]:
                    self.engaged[action] = True
                    self.actions_total[action] += 1
                    self._note(f"overload.engage:{action}", overall)
            # ...release in reverse
            for action, arm_level in reversed(ACTIONS):
                want = LEVEL_NUM[overall] >= LEVEL_NUM[arm_level]
                if not want and self.engaged[action]:
                    self.engaged[action] = False
                    self._note(f"overload.clear:{action}", overall)
            clamped = self.engaged["tap-clamp"]
        if clamped:
            self._shed_laggard_taps()

    def _note(self, kind: str, overall: str) -> None:
        detail = " ".join(
            f"{r}={self.pressure[r]:.2f}/{self.levels[r]}"
            for r in RESOURCES
        )
        self.engine._plog_append(kind, f"level={overall} {detail}")
        with self._lock:
            self.events.append({
                "wallMs": int(self._now_ms()),
                "kind": kind,
                "level": overall,
                "pressure": {
                    r: round(self.pressure[r], 3) for r in RESOURCES
                },
            })

    def _shed_laggard_taps(self) -> None:
        """While tap-clamp is engaged, disconnect taps lagging past the
        bound — terminal gap marker naming overload, never a silent
        stall."""
        registry = getattr(self.engine, "push_registry", None)
        if registry is None:
            return
        bound = int(self._prop(cfg.OVERLOAD_TAP_LAG_BOUND, 0))
        try:
            shed = registry.shed_laggards(bound)
        except Exception as e:  # noqa: BLE001 — shedding must not kill
            self.engine._on_error("overload.tap.shed", e)  # the monitor
            return
        if shed:
            with self._lock:
                self.taps_disconnected += shed
            self._note("overload.engage:tap-shed", self._overall_level())

    # ------------------------------------------------------- action seams
    def admission_allowed(self) -> bool:
        """False while the admission action is engaged: REST must answer
        new transient pull/push queries with 429 + Retry-After."""
        return not (self.enabled() and self.engaged["admission"])

    def retry_after_s(self) -> int:
        return max(1, int(self._prop(cfg.OVERLOAD_RETRY_AFTER_S, 1)))

    def note_shed(self) -> None:
        """One transient request answered 429 by admission control."""
        with self._lock:
            self.shed_requests += 1

    def tap_poll_rows(self, configured: int) -> int:
        """Push-tap poll clamp: the configured max while released, the
        overload clamp while tap-clamp is engaged."""
        if not self.engaged["tap-clamp"]:
            return configured
        clamp = int(self._prop(cfg.OVERLOAD_TAP_POLL_ROWS, 512))
        return max(1, min(configured, clamp))

    def poll_rows(self, handle, requested: int) -> int:
        """Source-pacing clamp for one query's poll tick, ordered by
        ksql.query.priority: below-top-tier queries shed to the clamp
        floor, top-tier queries keep 4x the floor.  Sinks stay live —
        every query still polls every tick, just fewer records."""
        if not self.engaged["source-pacing"]:
            return requested
        clamp = max(1, int(self._prop(cfg.OVERLOAD_POLL_CLAMP_ROWS, 128)))
        top = max(
            (int(getattr(h, "priority", 100))
             for h in self.engine.queries.values() if h.is_running()),
            default=100,
        )
        if int(getattr(handle, "priority", 100)) >= top:
            return min(requested, clamp * 4)
        return min(requested, clamp)

    def defer_elective(self) -> bool:
        """True while elective work (rescale / regrow / MQO attach — each
        costs compiles) must gate off."""
        return self.enabled() and self.engaged["defer-elective"]

    # -------------------------------------------------------- observation
    def stats(self) -> Dict[str, Any]:
        """The /metrics JSON section (and the Prometheus branch's input):
        per-resource levels+pressure, engaged actions, lifetime
        counters."""
        with self._lock:
            return {
                "level": self._overall_level(),
                "state": {r: LEVEL_NUM[self.levels[r]] for r in RESOURCES},
                "pressure": {
                    r: round(self.pressure[r], 4) for r in RESOURCES
                },
                "engaged": {a: int(self.engaged[a]) for a, _ in ACTIONS},
                "actions-total": dict(self.actions_total),
                "shed-requests-total": self.shed_requests,
                "taps-disconnected-total": self.taps_disconnected,
                "samples-total": self.samples,
                "monitor-errors-total": self.monitor_errors,
            }

    def alerts_view(self) -> Dict[str, Any]:
        """The /alerts evidence section: current posture + the bounded
        engage/clear event ring."""
        with self._lock:
            return {
                "level": self._overall_level(),
                "levels": dict(self.levels),
                "engaged": [a for a, _ in ACTIONS if self.engaged[a]],
                "events": [dict(ev) for ev in self.events],
            }

    # ------------------------------------------------------ monitor thread
    def start_monitor(self) -> None:
        """Server mode: a dedicated sampling thread, so overload is
        observed (and admission reacts) even while a poll tick holds the
        engine lock through a long device compile."""
        if not self.enabled() or self._monitor_thread is not None:
            return
        self._stop.clear()  # graftlint: owner=main
        self._monitor_thread = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="overload-monitor",
        )
        self._monitor_thread.start()

    # thread entrypoint: the server-mode sampling loop runs concurrently
    # with HTTP handler threads and the engine poll loop; every shared
    # mutation funnels through maybe_sample's manager lock
    # graftlint: entrypoint=overload-monitor
    def _monitor_loop(self) -> None:
        while not self._stop.is_set():
            self.maybe_sample()
            interval = int(self._prop(cfg.OVERLOAD_INTERVAL_MS, 1000))
            self._stop.wait(max(0.01, interval / 1000.0 / 2))

    def stop(self) -> None:
        self._stop.set()
        t = self._monitor_thread
        if t is not None:
            t.join(timeout=5)
            self._monitor_thread = None
