"""QTT harness functions — the reference's test-jar UDFs/UDAFs/UDTFs.

The reference registers these through its functional-test harness (classes
under ksqldb-engine/src/test/java/io/confluent/ksql/function/udf and
.../udaf, plus udf-example.jar's ToStruct); QTT case files call them by
name.  This module is the extension-dir equivalent, loaded through
ksql.extension.dir (UserFunctionLoader analog) so those cases execute for
real instead of being skipped.

Semantics mirror the cited Java sources exactly — including thrown
messages, proto of multi/variadic argument handling, and Java
stringification (Struct{A=bar}) where QTT expectations depend on it.
"""

from ksql_tpu.functions.ext import KsqlFunctionError, SqlType, udaf, udf, udtf

# ---------------------------------------------------------------- scalars


# TestUdf.java: each overload returns its own method name
@udf("TEST_UDF", params="INT, STRING", returns="STRING")
def _test_udf_int_string(arg1, arg2):
    return "doStuffIntString"


@udf("TEST_UDF", params="BIGINT, STRING", returns="STRING")
def _test_udf_long_string(arg1, arg2):
    return "doStuffLongString"


@udf("TEST_UDF", params="BIGINT, BIGINT, STRING", returns="STRING")
def _test_udf_long_long_string(arg1, arg2, arg3):
    return "doStuffLongLongString"


@udf("TEST_UDF", params="", returns="STRUCT<A VARCHAR>")
def _test_udf_return_struct():
    return {"A": "foo"}


@udf("TEST_UDF", params="BIGINT...", returns="STRING")
def _test_udf_long_varargs(*longs):
    return "doStuffLongVarargs"


@udf("TEST_UDF", params="STRUCT<A VARCHAR>", returns="STRING")
def _test_udf_struct(struct):
    return None if struct is None else struct.get("A")


# WhenCondition.java: proves CASE branches evaluate lazily
@udf("WHENCONDITION", params="BOOLEAN, BOOLEAN", returns="BOOLEAN",
     null_tolerant=False)
def _when_condition(ret_value, should_be_evaluated):
    if not should_be_evaluated:
        raise KsqlFunctionError("When condition in case is not running lazily!")
    return ret_value


# WhenResult.java: proves CASE results evaluate lazily
@udf("WHENRESULT", params="INT, BOOLEAN", returns="INT", null_tolerant=False)
def _when_result(ret_value, should_be_evaluated):
    if not should_be_evaluated:
        raise KsqlFunctionError("Then result in case is not running lazily!")
    return ret_value


# BadUdf.java: throws exceptions when called
@udf("BAD_UDF", params="INT", returns="STRING", null_tolerant=False)
def _bad_udf_blow_up(arg1):
    raise KsqlFunctionError("boom!")


@udf("BAD_UDF", params="BOOLEAN", returns="INT", null_tolerant=False)
def _bad_udf_might_throw(arg):
    if arg:
        raise KsqlFunctionError("You asked me to throw...")
    return 0


@udf("BAD_UDF", params="STRING", returns="STRING", stateful=True)
def _bad_udf_return_null():
    # returns null every other invocation (stateful across rows of a query)
    state = {"i": 0}

    def call(arg):
        i = state["i"]
        state["i"] += 1
        return None if i % 2 == 0 else arg

    return call


# ToStruct.java (udf-example.jar): wraps a string with a struct
@udf("TOSTRUCT", params="STRING", returns="STRUCT<A VARCHAR>")
def _to_struct(value):
    return {"A": value}


# ------------------------------------------------------------------ UDAFs


# TestUdaf.java: sums with TableUdaf undo for long/int, plain for double,
# and a struct variant summing fields A and B
@udaf("TEST_UDAF", params="BIGINT", returns="BIGINT")
class _TestUdafLong:
    def initialize(self):
        return 0

    def aggregate(self, value, agg):
        return agg + (value or 0)

    def merge(self, a, b):
        return a + b

    def map(self, agg):
        return agg

    def undo(self, value, agg):
        return agg - (value or 0)


@udaf("TEST_UDAF", params="INT", returns="BIGINT")
class _TestUdafInt(_TestUdafLong):
    pass


@udaf("TEST_UDAF", params="DOUBLE", returns="DOUBLE")
class _TestUdafDouble:
    def initialize(self):
        return 0.0

    def aggregate(self, value, agg):
        return agg + (value or 0.0)

    def merge(self, a, b):
        return a + b

    def map(self, agg):
        return agg


@udaf("TEST_UDAF", params="STRUCT<A INTEGER, B INTEGER>",
      returns="STRUCT<A INTEGER, B INTEGER>")
class _TestUdafStruct:
    def initialize(self):
        return {"A": 0, "B": 0}

    def aggregate(self, cur, agg):
        return {"A": agg["A"] + cur["A"], "B": agg["B"] + cur["B"]}

    def merge(self, a, b):
        return self.aggregate(a, b)

    def map(self, agg):
        return agg


def _str_len(s):
    return len(s) if s is not None else 0


# VarArgUdaf.java: sum of the long + lengths of the variadic strings
@udaf("VAR_ARG", params="BIGINT, STRING...", returns="BIGINT")
class _VarArg:
    def initialize(self):
        return 0

    def aggregate(self, cur, agg):
        first, strs = cur
        return agg + (first or 0) + sum(_str_len(s) for s in strs)

    def merge(self, a, b):
        return a + b

    def map(self, agg):
        return agg


# MiddleVarArgUdaf.java: like VAR_ARG plus two init ints added at map()
@udaf("MID_VAR_ARG", params="BIGINT, STRING...", init_params="INT, INT",
      returns="BIGINT")
class _MidVarArg(_VarArg):
    def __init__(self, first, second):
        self.constant = first + second

    def map(self, agg):
        return agg + self.constant


# MultiArgUdaf.java: Pair<Long,String> cols, (int, string...) init args
@udaf("MULTI_ARG", params="BIGINT, STRING", init_params="INT, STRING...",
      returns="BIGINT")
class _MultiArg:
    def __init__(self, init_arg1, *init_arg2):
        self.init_val = init_arg1 + sum(len(s) for s in init_arg2)

    def initialize(self):
        return self.init_val

    def aggregate(self, cur, agg):
        first, second = cur
        return agg + (first or 0) + _str_len(second)

    def merge(self, a, b):
        return a + b

    def map(self, agg):
        return agg


# FourArgUdaf.java / FiveArgUdaf.java
@udaf("FOUR_ARG", params="BIGINT, STRING, STRING, STRING",
      init_params="INT, STRING...", returns="BIGINT")
class _FourArg(_MultiArg):
    def aggregate(self, cur, agg):
        first, s2, s3, s4 = cur
        return agg + (first or 0) + _str_len(s2) + _str_len(s3) + _str_len(s4)


@udaf("FIVE_ARG", params="BIGINT, STRING, STRING, STRING, INT",
      init_params="INT, STRING...", returns="BIGINT")
class _FiveArg(_MultiArg):
    def aggregate(self, cur, agg):
        first, s2, s3, s4, fifth = cur
        return (agg + (first or 0) + _str_len(s2) + _str_len(s3)
                + _str_len(s4) + (fifth or 0))


# GenericVarArgUdaf.java: array of first-arg values where ALL cols non-null;
# the variadic group is VariadicArgs<C> — one generic type, so mixed-type
# variadic args must fail resolution ("wrong argument types" case)
@udaf("GENERIC_VAR_ARG", params="A, B, C...",
      returns=lambda ts: SqlType.array(ts[0]),
      device_kind="collect_all_valid")
class _GenericVarArg:
    def initialize(self):
        return []

    def aggregate(self, cur, agg):
        left, mid, rest = cur
        if left is not None and mid is not None and all(
            r is not None for r in rest
        ):
            return agg + [left]
        return agg

    def merge(self, a, b):
        return a + b

    def map(self, agg):
        return agg


# ObjVarColArgUdaf.java: same but Pair<Integer, VariadicArgs<Object>>
@udaf("OBJ_COL_ARG", params="INT, ANY...",
      returns=lambda ts: SqlType.array(ts[0]),
      device_kind="collect_all_valid")
class _ObjColArg:
    def initialize(self):
        return []

    def aggregate(self, cur, agg):
        left, rest = cur
        if left is not None and all(r is not None for r in rest):
            return agg + [left]
        return agg

    def merge(self, a, b):
        return a + b

    def map(self, agg):
        return agg


# ------------------------------------------------------------------ UDTFs


def _java_str(v, t=None):
    """Java String.valueOf / toString for TestUdtf's string outputs."""
    import decimal

    from ksql_tpu.execution.interpreter import java_double_str

    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return java_double_str(v)
    if isinstance(v, decimal.Decimal):
        return str(v)
    if isinstance(v, dict):  # Struct.toString(): Struct{A=bar,B=2}
        inner = ",".join(f"{k}={_java_str(x)}" for k, x in v.items()
                         if x is not None)
        return "Struct{" + inner + "}"
    return str(v)


# TestUdtf.java: standardParams — one string per scalar argument
@udtf("TEST_UDTF",
      params="INT, BIGINT, DOUBLE, BOOLEAN, STRING, DECIMAL(20, 10), "
             "STRUCT<A VARCHAR>",
      returns="STRING")
def _test_udtf_standard(i, l, d, b, s, bd, struct):  # noqa: E741
    return [_java_str(i), _java_str(l), _java_str(d), _java_str(b), s,
            _java_str(bd), _java_str(struct)]


@udtf("TEST_UDTF",
      params="ARRAY<INT>, ARRAY<BIGINT>, ARRAY<DOUBLE>, ARRAY<BOOLEAN>, "
             "ARRAY<STRING>, ARRAY<DECIMAL(20, 10)>, ARRAY<STRUCT<A VARCHAR>>",
      returns="STRING")
def _test_udtf_lists(i, l, d, b, s, bd, struct):  # noqa: E741
    return [_java_str(i[0]), _java_str(l[0]), _java_str(d[0]),
            _java_str(b[0]), s[0], _java_str(bd[0]), _java_str(struct[0])]


@udtf("TEST_UDTF",
      params="MAP<STRING, INT>, MAP<STRING, BIGINT>, MAP<STRING, DOUBLE>, "
             "MAP<STRING, BOOLEAN>, MAP<STRING, STRING>, "
             "MAP<STRING, DECIMAL(20, 10)>, MAP<STRING, STRUCT<A VARCHAR>>",
      returns="STRING")
def _test_udtf_maps(i, l, d, b, s, bd, struct):  # noqa: E741
    def first(m):
        return next(iter(m.values()))

    return [_java_str(first(i)), _java_str(first(l)), _java_str(first(d)),
            _java_str(first(b)), first(s), _java_str(first(bd)),
            _java_str(first(struct))]


# TestUdtf.java listXReturn: identity single-element lists per type
@udtf("TEST_UDTF", params="INT", returns="INT")
def _test_udtf_int(i):
    return [i]


@udtf("TEST_UDTF", params="BIGINT", returns="BIGINT")
def _test_udtf_long(l):  # noqa: E741
    return [l]


@udtf("TEST_UDTF", params="DOUBLE", returns="DOUBLE")
def _test_udtf_double(d):
    return [d]


@udtf("TEST_UDTF", params="BOOLEAN", returns="BOOLEAN")
def _test_udtf_bool(b):
    return [b]


@udtf("TEST_UDTF", params="STRING", returns="STRING")
def _test_udtf_string(s):
    return [s]


# listBigDecimalReturnWithSchemaProvider: fixed DECIMAL(30, 10) result
@udtf("TEST_UDTF", params="DECIMAL(20, 10)", returns="DECIMAL(30, 10)")
def _test_udtf_decimal(bd):
    return [bd]


@udtf("TEST_UDTF", params="STRUCT<A VARCHAR>", returns="STRUCT<A VARCHAR>")
def _test_udtf_struct(struct):
    return [struct]


# ThrowingUdtf.java
@udtf("THROWING_UDTF", params="BOOLEAN", returns="BOOLEAN")
def _throwing_udtf(should_throw):
    if should_throw:
        raise KsqlFunctionError("You asked me to throw...")
    return [should_throw]
