"""Measure XLA-backend placement across the QTT corpus.

For every corpus case, plans its statements on a device-backend engine
(CPU jax; construction is eval_shape-only) and counts which persistent
queries lowered to the device vs fell back to the oracle.  Writes
device_coverage.json: {files, cases, queries, device_queries, share,
fallback_reasons (top)}.
"""
import collections
import json
import os
import sys
import concurrent.futures as cf

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

QTT_DIR = "/root/reference/ksqldb-functional-tests/src/test/resources/query-validation-tests"


def scan_file(fname):
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    import re

    from ksql_tpu.common.config import (
        PROCESSING_LOG_TOPIC_AUTO_CREATE,
        RUNTIME_BACKEND,
        KsqlConfig,
    )
    from ksql_tpu.engine.engine import KsqlEngine
    from ksql_tpu.tools.qtt import _expand_matrix

    with open(os.path.join(QTT_DIR, fname)) as f:
        text = re.sub(r"^\s*//.*$", "", f.read(), flags=re.M)
    doc = json.loads(text)
    total = device = cases = 0
    reasons = collections.Counter()
    for case in doc.get("tests", ()):
        for variant in _expand_matrix(case):
            if "expectedException" in variant:
                continue
            cases += 1
            engine = KsqlEngine(KsqlConfig({
                RUNTIME_BACKEND: "device",
                PROCESSING_LOG_TOPIC_AUTO_CREATE: False,
            }))
            engine.session_properties.update(variant.get("properties", {}))
            try:
                for t in variant.get("topics", ()):
                    name = t if isinstance(t, str) else t["name"]
                    engine.broker.create_topic(name, 4)
                    if not isinstance(t, str):
                        for kind in ("key", "value"):
                            if t.get(f"{kind}Schema") is not None:
                                engine.schema_registry.add_pending(
                                    f"{name}-{kind}",
                                    str(t.get(f"{kind}Format", "AVRO")),
                                    t[f"{kind}Schema"],
                                    tuple(r.get("schema") for r in
                                          t.get(f"{kind}SchemaReferences", ())),
                                )
                for rec in variant.get("inputs", ()):
                    engine.broker.create_topic(rec["topic"], 4)
                for stmt in variant.get("statements", ()):
                    for prepared in engine.parse(stmt):
                        engine.execute_statement(prepared)
            except Exception:
                continue
            for h in engine.queries.values():
                total += 1
                if h.backend == "device":
                    device += 1
            for reason, cnt in engine.fallback_reasons.items():
                reasons[reason.split(" (")[0][:70]] += cnt
    return fname, cases, total, device, reasons


def main():
    files = sorted(f for f in os.listdir(QTT_DIR) if f.endswith(".json"))
    cases = queries = device = 0
    reasons = collections.Counter()
    with cf.ProcessPoolExecutor(max_workers=8) as pool:
        for fname, c, t, d, r in pool.map(scan_file, files):
            cases += c
            queries += t
            device += d
            reasons.update(r)
    out = {
        "files": len(files),
        "cases": cases,
        "persistent_queries": queries,
        "device_queries": device,
        "device_share": round(device / max(queries, 1), 4),
        "top_fallback_reasons": dict(reasons.most_common(15)),
    }
    with open(os.path.join(os.path.dirname(__file__), "..",
                           "device_coverage.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
