#!/usr/bin/env python
"""obs_report — render a query's retained telemetry timeline (ISSUE 18).

Fetches ``GET /timeline/<qid>`` from a running ksql-tpu server and
renders the frames as a terminal report: per-interval throughput/ticks,
watermark lag, per-stage p50/p99, per-shard balance (with the hot-shard
share the skew detector judges), lifecycle annotations in context, and
the aggregated e2e latency distribution.  ``--json`` emits the fetched
body plus the derived summary for tooling.

Usage:

  python scripts/obs_report.py CTAS_C_7                    full report
  python scripts/obs_report.py CTAS_C_7 --since 123456     frames after
                                                           that interval
                                                           seq (cursor)
  python scripts/obs_report.py CTAS_C_7 --json             machine output
  python scripts/obs_report.py CTAS_C_7 \
      --server http://host:8088                            remote server

Exit codes: 0 = rendered, 1 = HTTP/owner error, 2 = usage error.
"""

import argparse
import json
import sys
import urllib.error
import urllib.request

BAR_W = 24


def fetch_timeline(server, qid, since=None, timeout_s=10.0):
    url = f"{server.rstrip('/')}/timeline/{qid}"
    if since is not None:
        url += f"?since={int(since)}"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _fmt_ms(v):
    if v is None:
        return "-"
    if v >= 10000:
        return f"{v / 1000.0:.1f}s"
    return f"{v:.0f}ms" if v >= 10 else f"{v:.2f}ms"


def _fmt_time(ms):
    import datetime

    return datetime.datetime.fromtimestamp(ms / 1000.0).strftime("%H:%M:%S")


def _bar(frac, width=BAR_W):
    n = max(0, min(width, int(round(frac * width))))
    return "#" * n + "." * (width - n)


def e2e_percentile(bounds_s, counts, p):
    """Interpolated percentile in ms over summed bucket counts (the same
    estimate common/metrics.py E2eHistogram.percentile makes)."""
    total = sum(counts)
    if not total:
        return None
    target = p * total
    cum = 0
    for i, c in enumerate(counts):
        if not c:
            continue
        cum += c
        if cum >= target:
            lo = bounds_s[i - 1] if i > 0 else 0.0
            hi = bounds_s[i] if i < len(bounds_s) else bounds_s[-1]
            frac = (target - (cum - c)) / c
            return round((lo + (hi - lo) * frac) * 1000.0, 3)
    return round(bounds_s[-1] * 1000.0, 3)


def summarize(body):
    """Cross-frame aggregates: totals, stage p50/max-p99, shard totals +
    hot share, summed e2e buckets, flattened annotations."""
    frames = body.get("frames", [])
    bounds = body.get("e2eBucketsS") or []
    total_rows = sum(f.get("rows", 0) for f in frames)
    total_ticks = sum(f.get("ticks", 0) for f in frames)
    err_ticks = sum(f.get("errTicks", 0) for f in frames)
    stages = {}
    for f in frames:
        for name, st in (f.get("stages") or {}).items():
            agg = stages.setdefault(
                name, {"ticks": 0, "totalMs": 0.0, "p50s": [], "p99s": []}
            )
            agg["ticks"] += st.get("ticks", 0)
            agg["totalMs"] += st.get("totalMs", 0.0)
            if st.get("p50Ms") is not None:
                agg["p50s"].append(st["p50Ms"])
            if st.get("p99Ms") is not None:
                agg["p99s"].append(st["p99Ms"])
    stage_rows = []
    for name in sorted(stages):
        agg = stages[name]
        p50s = sorted(agg["p50s"])
        stage_rows.append({
            "stage": name,
            "ticks": agg["ticks"],
            "totalMs": round(agg["totalMs"], 3),
            "p50Ms": p50s[len(p50s) // 2] if p50s else None,
            "p99Ms": max(agg["p99s"]) if agg["p99s"] else None,
        })
    shard_rows = None
    for f in frames:
        sh = f.get("shards")
        if not sh or not sh.get("rows"):
            continue
        rows = sh["rows"]
        if shard_rows is None or len(shard_rows) != len(rows):
            shard_rows = list(rows)
        else:
            shard_rows = [a + b for a, b in zip(shard_rows, rows)]
    hot = None
    if shard_rows and sum(shard_rows) > 0 and len(shard_rows) > 1:
        i = max(range(len(shard_rows)), key=shard_rows.__getitem__)
        hot = {"shard": i, "share": shard_rows[i] / sum(shard_rows)}
    e2e_counts = [0] * (len(bounds) + 1)
    for f in frames:
        for i, c in enumerate((f.get("e2e") or {}).get("counts") or []):
            if i < len(e2e_counts):
                e2e_counts[i] += c
    annotations = [
        {**a, "seq": f["seq"]}
        for f in frames for a in f.get("annotations", [])
    ]
    return {
        "frames": len(frames),
        "coalesced": body.get("coalesced", 0),
        "rows": total_rows,
        "ticks": total_ticks,
        "errTicks": err_ticks,
        "stages": stage_rows,
        "shardRows": shard_rows,
        "hotShard": hot,
        "e2eCounts": e2e_counts,
        "e2eP50Ms": e2e_percentile(bounds, e2e_counts, 0.50),
        "e2eP99Ms": e2e_percentile(bounds, e2e_counts, 0.99),
        "annotations": annotations,
    }


def render(body, out=sys.stdout):
    s = summarize(body)
    frames = body.get("frames", [])
    interval_ms = body.get("intervalMs", 0)
    w = out.write
    w(
        f"timeline {body.get('ownerId')} — interval {interval_ms}ms, "
        f"{s['frames']} frame(s), {s['coalesced']} idle coalesced, "
        f"nextSince={body.get('nextSince')}\n"
    )
    if not frames:
        w("  (no retained frames — query idle or telemetry disabled)\n")
        return
    peak = max(f.get("rows", 0) for f in frames) or 1
    w(
        f"\n  {'seq':>12} {'time':>8} {'ticks':>5} {'rows':>8} "
        f"{'rps':>9} {'wmLag':>8}  activity\n"
    )
    for f in frames:
        marks = "".join(
            sorted({a["kind"][0].upper() for a in f.get("annotations", [])})
        )
        open_mark = " (open)" if f.get("open") else ""
        w(
            f"  {f['seq']:>12} {_fmt_time(f['startMs']):>8}"
            f" {f.get('ticks', 0):>5} {f.get('rows', 0):>8}"
            f" {f.get('throughputRps', 0):>9.1f}"
            f" {_fmt_ms(f.get('watermarkLagMs')):>8}"
            f"  {_bar(f.get('rows', 0) / peak)} {marks}{open_mark}\n"
        )
    if s["stages"]:
        w("\n  stage latency over retained frames (per-interval fold)\n")
        w(f"  {'stage':<24} {'ticks':>6} {'p50':>9} {'p99':>9} "
          f"{'total':>10}\n")
        for st in s["stages"]:
            w(
                f"  {st['stage']:<24} {st['ticks']:>6}"
                f" {_fmt_ms(st['p50Ms']):>9} {_fmt_ms(st['p99Ms']):>9}"
                f" {_fmt_ms(st['totalMs']):>10}\n"
            )
    if s["shardRows"]:
        total = sum(s["shardRows"]) or 1
        w("\n  shard balance (rows over retained frames)\n")
        for i, r in enumerate(s["shardRows"]):
            hot = (
                "  << hot"
                if s["hotShard"] and s["hotShard"]["shard"] == i else ""
            )
            w(
                f"  shard {i:>3} {r:>10} {r / total:>6.1%} "
                f"{_bar(r / total)}{hot}\n"
            )
    if sum(s["e2eCounts"]):
        bounds = body.get("e2eBucketsS") or []
        total = sum(s["e2eCounts"])
        w(
            f"\n  e2e latency (n={total}, p50={_fmt_ms(s['e2eP50Ms'])}, "
            f"p99={_fmt_ms(s['e2eP99Ms'])})\n"
        )
        for i, c in enumerate(s["e2eCounts"]):
            if not c:
                continue
            label = (
                f"<= {bounds[i]:g}s" if i < len(bounds) else "+Inf"
            )
            w(f"  {label:>12} {c:>8} {_bar(c / total)}\n")
    if s["annotations"]:
        w("\n  annotations (lifecycle events on their interval)\n")
        for a in s["annotations"]:
            w(
                f"  seq {a['seq']} {_fmt_time(a['wallMs'])} "
                f"[{a['kind']}] {a.get('detail', '')}\n"
            )


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="render a query's retained telemetry timeline"
    )
    ap.add_argument("query_id", help="query or push-pipeline id")
    ap.add_argument("--server", default="http://localhost:8088",
                    help="ksql-tpu REST server (default %(default)s)")
    ap.add_argument("--since", type=int, default=None,
                    help="only frames with interval seq > SINCE")
    ap.add_argument("--json", action="store_true",
                    help="emit the fetched body + derived summary as JSON")
    ap.add_argument("--timeout-s", type=float, default=10.0)
    args = ap.parse_args(argv)
    try:
        body = fetch_timeline(
            args.server, args.query_id, args.since, args.timeout_s
        )
    except urllib.error.HTTPError as e:
        print(f"error: {e.code} {e.reason} for {args.query_id}",
              file=sys.stderr)
        return 1
    except (urllib.error.URLError, OSError) as e:
        print(f"error: cannot reach {args.server}: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(
            {**body, "summary": summarize(body)}, indent=2, sort_keys=True
        ))
    else:
        render(body)
    return 0


if __name__ == "__main__":
    sys.exit(main())
