#!/usr/bin/env python
"""perfgate — the per-stage perf regression gate (ISSUE 11 tentpole).

Runs the pinned bench workload set (headline tumbling count,
hopping_sum_group_by, window_family, mqo_dashboard, push_fanout,
engine_e2e_dist) N
times on the deadline-proof bench.py harness, folds the runs into
medians (throughput median + per-stage median-of-p99 off the PR-3
flight-recorder accumulators), and compares them against a committed
baseline with variance-aware thresholds.  A regression fails LOUDLY
with a per-stage diff table naming the regressed workload + stage.

Usage:

  python scripts/perfgate.py                      gate HEAD vs the
                                                  committed baseline
                                                  (PERF_BASELINE.json)
  python scripts/perfgate.py --write-baseline     snapshot a new baseline
  python scripts/perfgate.py --smoke              force BENCH_SMOKE sizes
                                                  (auto-enabled when the
                                                  baseline was taken in
                                                  smoke mode)
  python scripts/perfgate.py --runs 5             more runs, tighter
                                                  medians
  python scripts/perfgate.py --from-runs f.json   re-gate saved runs
                                                  (no benches run)

Exit codes: 0 = pass, 1 = regression (stage-named), 2 = usage error
(missing/mismatched baseline, too few runs).

Each bench invocation is a child process under its own watchdog budget
(the PR-7 harness's own containment applies per bench inside it); the
whole gate also respects --budget-s.  The committed baseline records the
platform + device count it was measured on — gating CPU numbers against
an accelerator baseline (or vice versa) is refused as a usage error
instead of producing nonsense verdicts.
"""

import argparse
import json
import os
import shlex
import subprocess
import sys
import time

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, ROOT)

from ksql_tpu.common.perfgate import (  # noqa: E402
    BENCH_ONLY,
    DEFAULT_THRESHOLDS,
    PerfGateUsageError,
    compare,
    diff_table,
    load_baseline,
    make_baseline,
    selected_workloads,
    summarize,
)

DEFAULT_BASELINE = os.path.join(ROOT, "PERF_BASELINE.json")


def _parse_bench_stdout(stdout: str):
    """The LAST parseable JSON object line is the most complete result
    (bench.py re-emits after every config)."""
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


def run_benches(args) -> list:
    """Run bench.py ``args.runs`` times over the pinned workload set,
    returning the parsed final JSON line of each run."""
    cmd = (
        shlex.split(args.bench_cmd) if args.bench_cmd
        else [sys.executable, os.path.join(ROOT, "bench.py")]
    )
    env = dict(os.environ)
    env["BENCH_ONLY"] = args.only or BENCH_ONLY
    env["BENCH_BUDGET_S"] = str(args.bench_budget_s)
    if args.smoke:
        env["BENCH_SMOKE"] = "1"
    runs = []
    t0 = time.monotonic()
    for i in range(args.runs):
        left = args.budget_s - (time.monotonic() - t0)
        if left <= 30.0 and runs:
            enough = len(runs) >= args.min_runs
            print(
                f"perfgate: budget exhausted after {len(runs)} runs "
                f"(--budget-s {args.budget_s:.0f}); "
                + ("gating on what landed" if enough else
                   f"fewer than --min-runs {args.min_runs} landed — the "
                   "gate will refuse (raise --budget-s)"),
                file=sys.stderr, flush=True,
            )
            break
        print(
            f"perfgate: bench run {i + 1}/{args.runs} "
            f"({left:.0f}s of budget left)",
            file=sys.stderr, flush=True,
        )
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, cwd=ROOT, env=env,
                timeout=max(60.0, left),
            )
        except subprocess.TimeoutExpired:
            print(
                f"perfgate: bench run {i + 1} blew the remaining budget; "
                "stopping", file=sys.stderr, flush=True,
            )
            break
        parsed = _parse_bench_stdout(proc.stdout)
        if parsed is None:
            print(
                f"perfgate: bench run {i + 1} produced no JSON line "
                f"(rc={proc.returncode}): "
                f"{proc.stderr.strip().splitlines()[-3:]}",
                file=sys.stderr, flush=True,
            )
            continue
        runs.append(parsed)
    return runs


def _meta_of(runs, args) -> dict:
    extra = (runs[0].get("extra") or {}) if runs else {}
    return {
        "platform": extra.get("platform"),
        "devices": extra.get("devices"),
        "smoke": bool(args.smoke),
        "runs": len(runs),
        "benchOnly": args.only or BENCH_ONLY,
        "createdAtMs": int(time.time() * 1000),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline JSON path (default PERF_BASELINE.json)")
    p.add_argument("--write-baseline", action="store_true",
                   help="snapshot the runs as the new baseline and exit 0")
    p.add_argument("--runs", type=int, default=3,
                   help="bench rounds to median over (gate needs >= 3)")
    p.add_argument("--min-runs", type=int, default=3,
                   help="fewest usable runs the gate accepts")
    p.add_argument("--smoke", action="store_true",
                   help="BENCH_SMOKE sizes (auto when the baseline is "
                        "a smoke baseline)")
    p.add_argument("--only", default="",
                   help="override the pinned BENCH_ONLY pattern")
    p.add_argument("--bench-cmd", default="",
                   help="override the bench command (tests use a stub)")
    p.add_argument("--budget-s", type=float, default=3600.0,
                   help="wall budget for all runs together")
    p.add_argument("--bench-budget-s", type=float, default=900.0,
                   help="BENCH_BUDGET_S per bench run")
    p.add_argument("--save-runs", default="",
                   help="write the parsed run lines to this JSON file")
    p.add_argument("--from-runs", default="",
                   help="gate saved run lines instead of running benches")
    p.add_argument("--json", dest="json_out", default="",
                   help="write the machine-readable verdict here")
    p.add_argument("--throughput-ratio", type=float, default=None,
                   help="override the baseline's throughput floor ratio")
    p.add_argument("--stage-ratio", type=float, default=None,
                   help="override the baseline's stage p99 ceiling ratio")
    args = p.parse_args(argv)

    try:
        baseline = None
        if not args.write_baseline:
            # load FIRST: a missing baseline must be a usage error before
            # any expensive bench runs — as must a run count that cannot
            # satisfy the median requirement (don't burn ~10 min of
            # benches to report an error decidable upfront)
            baseline = load_baseline(args.baseline)
            if not args.from_runs and args.runs < args.min_runs:
                raise PerfGateUsageError(
                    f"--runs {args.runs} cannot satisfy --min-runs "
                    f"{args.min_runs}: the gate needs >= {args.min_runs} "
                    "usable runs to median over"
                )
            base_smoke = bool(baseline.get("meta", {}).get("smoke"))
            if base_smoke and not args.smoke:
                args.smoke = True  # match the baseline's mode
            elif args.smoke and not base_smoke and not args.from_runs:
                # mode mismatches are refused both ways, like platforms:
                # smoke corpora amortize cold compile differently and the
                # verdicts would be systematically wrong
                raise PerfGateUsageError(
                    "baseline was measured at full sizes but --smoke was "
                    "passed: re-snapshot with --write-baseline --smoke "
                    "or drop --smoke"
                )

        if args.from_runs:
            try:
                with open(args.from_runs) as f:
                    runs = json.load(f)
            except (OSError, ValueError) as e:
                raise PerfGateUsageError(
                    f"unreadable --from-runs {args.from_runs}: {e}"
                ) from e
        else:
            runs = run_benches(args)
        if args.save_runs:
            with open(args.save_runs, "w") as f:
                json.dump(runs, f, indent=1)

        if args.write_baseline:
            summary = summarize(runs, min_runs=min(args.min_runs,
                                                   args.runs))
            th = dict(DEFAULT_THRESHOLDS)
            if args.throughput_ratio is not None:
                th["throughput_ratio"] = args.throughput_ratio
            if args.stage_ratio is not None:
                th["stage_ratio"] = args.stage_ratio
            data = make_baseline(summary, _meta_of(runs, args), th)
            with open(args.baseline, "w") as f:
                json.dump(data, f, indent=1, sort_keys=True)
                f.write("\n")
            print(f"perfgate: baseline written to {args.baseline} "
                  f"({len(summary)} workloads over {len(runs)} runs)")
            return 0

        # ---- gate mode
        meta = baseline.get("meta", {})
        cur_platform = None
        for r in runs:
            cur_platform = (r.get("extra") or {}).get("platform")
            if cur_platform:
                break
        if (
            meta.get("platform") and cur_platform
            and meta["platform"] != cur_platform
        ):
            raise PerfGateUsageError(
                f"baseline was measured on platform="
                f"{meta['platform']} but this run is on {cur_platform}: "
                "cross-platform gating is meaningless — re-snapshot with "
                "--write-baseline on this platform"
            )
        cur_devices = next(
            (r.get("extra", {}).get("devices") for r in runs
             if (r.get("extra") or {}).get("devices")), None,
        )
        if (
            meta.get("devices") and cur_devices
            and meta["devices"] != cur_devices
        ):
            # same refusal as platforms: comparing an 8-device mesh
            # baseline against a 1-device host misjudges every
            # distributed number
            raise PerfGateUsageError(
                f"baseline was measured with devices={meta['devices']} "
                f"but this run sees {cur_devices}: re-snapshot with "
                "--write-baseline in this environment"
            )
        current = summarize(runs, min_runs=args.min_runs)
        overrides = {}
        if args.throughput_ratio is not None:
            overrides["throughput_ratio"] = args.throughput_ratio
        if args.stage_ratio is not None:
            overrides["stage_ratio"] = args.stage_ratio
        # workloads narrowed away by --only are deliberately absent —
        # only the still-selected set is held to the zero-evidence rule
        expected = selected_workloads(args.only) if args.only else None
        rows, regressions = compare(baseline, current, overrides,
                                    expected=expected,
                                    min_workload_runs=args.min_runs)
        print(diff_table(rows))
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump({
                    "ok": not regressions,
                    "rows": rows,
                    "regressions": regressions,
                    "current": current,
                    "baselineMeta": meta,
                }, f, indent=1)
        if regressions:
            print("\nPERFGATE FAIL — regressed:")
            for r in regressions:
                print(
                    f"  {r['workload']} / {r['stage']}: "
                    f"baseline={r['baseline']} current={r['current']} "
                    f"({r['verdict']})"
                )
            print(
                "(medians over "
                f"{max(w.get('runs', 0) for w in current.values())} runs; "
                "thresholds live in the baseline file — refresh with "
                "--write-baseline only for INTENDED perf changes)"
            )
            return 1
        print(f"\nPERFGATE OK ({len(current)} workloads vs "
              f"{os.path.relpath(args.baseline, ROOT)})")
        return 0
    except PerfGateUsageError as e:
        print(f"perfgate: usage error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
