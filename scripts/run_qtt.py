"""Run the full QTT corpus and dump summary + detailed failures.

Usage: python scripts/run_qtt.py [file-substring ...]
Writes qtt_status.json (per-file summary) and qtt_failures.txt (details).
"""
import json
import os
import sys
import concurrent.futures as cf

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

QTT_DIR = "/root/reference/ksqldb-functional-tests/src/test/resources/query-validation-tests"


def run_one(fname):
    if os.environ.get("QTT_BACKEND") == "device":
        # device-mode QTT runs on CPU jax: the one real TPU cannot take 8
        # compiling workers, and env vars are too late (the environment
        # preloads jax against the accelerator) — reconfigure explicitly
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError as e:
            import sys

            print(
                f"WARNING: could not pin QTT worker to CPU jax ({e}); "
                "device-mode cases may compile on the real accelerator",
                file=sys.stderr,
            )
    from ksql_tpu.tools.qtt import run_file
    path = os.path.join(QTT_DIR, fname)
    try:
        results = run_file(path)
    except Exception as e:
        return fname, None, f"{type(e).__name__}: {e}"
    return fname, results, None


def main():
    filters = sys.argv[1:]
    files = sorted(f for f in os.listdir(QTT_DIR) if f.endswith(".json"))
    if filters:
        files = [f for f in files if any(s in f for s in filters)]
    status = {}
    failures = []
    totals = {}
    with cf.ProcessPoolExecutor(max_workers=8) as ex:
        for fname, results, harness_err in ex.map(run_one, files):
            if harness_err:
                status[fname] = {"HARNESS_ERROR": harness_err}
                totals["HARNESS_ERROR"] = totals.get("HARNESS_ERROR", 0) + 1
                continue
            summ = {}
            for r in results:
                summ[r.status] = summ.get(r.status, 0) + 1
                totals[r.status] = totals.get(r.status, 0) + 1
                if r.status in ("FAIL", "ERROR"):
                    failures.append(f"{fname} :: {r.name} :: {r.status} :: {r.detail}")
            status[fname] = dict(sorted(summ.items()))
    if not filters and os.environ.get("QTT_BACKEND", "oracle") == "oracle":
        # the committed status/failure files track the oracle corpus;
        # device-mode sweeps report to stdout only
        with open("qtt_status.json", "w") as f:
            json.dump(status, f, indent=1, sort_keys=True)
        with open("qtt_failures.txt", "w") as f:
            f.write("\n".join(failures))
    else:
        print("\n".join(failures))
    npass = (totals.get("PASS", 0) + totals.get("XFAIL_MATCHED", 0)
             + totals.get("XFAIL_LOOSE", 0))
    ntot = sum(v for k, v in totals.items() if k != "SKIP")
    print(json.dumps(totals), f"parity={npass}/{ntot} = {npass/max(ntot,1):.1%}")


if __name__ == "__main__":
    main()
