#!/usr/bin/env python
"""Chaos soak: run a datagen workload under randomized fault rules and
assert no produced row is lost and the engine converges healthy.

Usage:
    JAX_PLATFORMS=cpu python scripts/chaos_soak.py [--seconds 10] [--seed 0]
        [--backend oracle|device] [--rate 200] [--corrupt]

The soak produces rows continuously while seeded random fault rules tear
reads, fail produces, and break device dispatch.  Faults are restricted to
the *recoverable* classes: injected serde corruption / poison records are
excluded by default — those are skipped-by-design (LogAndContinue), which
is row loss the at-least-once invariant intentionally permits.  Source
produces that fail are excluded from the expectation (the row never
entered the log — producer-side loss, not engine loss).

``--corrupt`` is the poison-coverage variant (ROADMAP "chaos_soak
coverage" item): corrupt-mode ``serde.deserialize`` faults are ADDED to
the menu and the invariant changes from "no rows lost" to "**no rows lost
silently**" — every produced row must either land in the sink or be
accounted for by a processing-log poison entry (or, for the rare
corruption that still parses, surface as a mutated sink row).

``--watch`` polls the health watchdog's alert view each soak iteration
(the same payload ``GET /alerts`` serves: current LAGGING/STALLED queries
with evidence) and fails the run if any STALLED query does NOT recover to
a non-alert state by convergence — chaos may wedge a query transiently,
but an un-recovered stall is a self-healing bug.

``--hang`` is the tick-deadline variant (PR 5): hang-mode faults block ONE
query's tick body (``stage.process`` on the oracle, ``device.dispatch`` on
the device backend) far past an armed ``ksql.query.tick.timeout.ms``.  The
run fails unless (a) every deadline-killed tick recovers — the victim ends
RUNNING and caught up, or terminal ERROR within ``ksql.query.retry.max`` —
and (b) the sibling query's committed offsets and watermark kept advancing
while the victim was wedged (no head-of-line blocking through the
synchronous poll loop).

``--rescale`` is the elastic-mesh variant (PR 9): two distributed queries
(a stateless projection and a COUNT aggregation) run under the usual
raise/delay fault mix plus a hang-mode device wedge contained by the tick
deadline, while the soak force-triggers grow/shrink cutovers (the
supervised drain/cutover ladder: commit-point checkpoint → fence → rebuild
at the new shard count → reshard-restore → resume).  Invariants: no
produced row is lost, neither query ends terminal, at least one grow and
one shrink completed, and the push session riding the projection saw a
BOUNDED number of gap markers across the cutovers.

``--fanout`` is the push-serving variant (ISSUE 10): ~50 filtered push
sessions over one stream share ONE registry pipeline while raise-mode
kills and short hang-mode wedges hit the shared pipeline
(``push.pipeline.step``) and its reads.  Invariants: exactly one shared
pipeline serves every tap, no tap ends terminal (every kill heals within
the retry budget, each incident = one gap marker per tap), and no rows
are lost beyond gap-marked spans — a tap that missed rows must have seen
an eviction gap naming the skipped offset span, and the total shortfall
is bounded by the registry's ring-evicted counter.

``--overload`` is the overload-manager variant (ISSUE 16): a REAL
``KsqlServer`` runs two persistent device-backed queries (different
``ksql.query.priority``) under a tight HBM budget and aggressive overload
thresholds while the soak floods it three ways at once — a producer
burst+stream that blows the lag thresholds, a tap storm (half the push
taps deliberately never polled, so they lag past the shed bound), and a
transient-query storm over real HTTP — plus injected ``overload.monitor``
faults the monitor must absorb.  Invariants: the process survives (the
server still answers /healthcheck), every shed transient request got a
real 429 + Retry-After (none hung), a mid-flood persistent DDL via /ksql
was still accepted, >= 1 degradation action engaged and ALL actions
cleared after the flood drained, laggard taps were disconnected with a
terminal gap marker naming overload (never silently stalled), zero
persistent queries ended terminal, and the persistent sinks match a
fault-free oracle twin fed the same records.

``--crash`` is the kill-9 durability variant (ISSUE 20): a REAL
``KsqlServer`` subprocess runs stateful carriers (windowed GROUP BY +
stream-stream join) over a command WAL, a checkpoint dir, and the
incremental changelog journal, and the harness SIGKILLs it at
randomized points — mid-tick, mid-checkpoint-save, and
mid-changelog-append (the latter two via env-armed one-shot hang
faults, so the kill lands inside the write and the journal keeps a
genuinely torn tail frame) — then restarts it on the same dirs.
Invariants: zero ACKed-then-lost rows vs a crash-free oracle twin fed
the dumped source topics, duplicate sink rows bounded by one in-flight
tick per crash (the emit-seq fence), the recovery replay window stays
ticks-since-last-checkpoint (scraped from /metrics at each restart,
never whole-batch), the torn tail was observed and then truncated
away, and the final restart replayed a changelog tail.  Runs two
seeds.

Exit code 0 = sink converged with a healthy final state and the active
invariant held; 1 = rows lost (silently, under --corrupt), query stuck,
un-recovered STALLED under --watch, or terminal ERROR.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, ".")

# the distributed variants (--rescale / --mesh) need a multi-device mesh;
# outside the test harness (which forces 8 virtual CPU devices in
# conftest) give the host platform the same shape BEFORE jax initializes
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

from ksql_tpu.common import config as cfg  # noqa: E402
from ksql_tpu.common import faults  # noqa: E402
from ksql_tpu.common.config import KsqlConfig  # noqa: E402
from ksql_tpu.engine.engine import KsqlEngine  # noqa: E402
from ksql_tpu.runtime.topics import Record  # noqa: E402

SRC_TOPIC = "soak_src"

#: recoverable fault menu the soak samples from: (point, match, mode, kwargs)
FAULT_MENU = [
    ("topic.read", SRC_TOPIC, "raise", {}),
    ("topic.produce", "SOAK_OUT", "raise", {}),  # sink emission faults
    ("topic.produce", SRC_TOPIC, "raise", {}),
    ("topic.read", SRC_TOPIC, "delay", {"delay_ms": 2.0}),
    ("device.dispatch", "", "raise", {}),
    ("checkpoint.save", "", "raise", {}),
]


def build_engine(backend: str) -> KsqlEngine:
    e = KsqlEngine(KsqlConfig({
        cfg.RUNTIME_BACKEND: backend,
        cfg.QUERY_RETRY_BACKOFF_INITIAL_MS: 1,
        cfg.QUERY_RETRY_BACKOFF_MAX_MS: 20,
        # stall verdicts within a soak-sized window (default 8 is tuned
        # for server-mode 20ms ticks; the soak polls slower)
        cfg.HEALTH_STALL_TICKS: 5,
    }))
    e.execute_sql(
        f"CREATE STREAM SOAK (ID BIGINT, V BIGINT) "
        f"WITH (kafka_topic='{SRC_TOPIC}', value_format='JSON');"
    )
    e.execute_sql("CREATE STREAM SOAK_OUT AS SELECT ID, V * 3 AS W FROM SOAK;")
    return e


def soak(seconds: float = 10.0, seed: int = 0, backend: str = "oracle",
         rate: int = 200, verbose: bool = True, corrupt: bool = False,
         watch: bool = False) -> dict:
    """Run the soak; returns a result dict (see keys below)."""
    rng = random.Random(seed)
    rules = []
    for i in range(rng.randint(2, 4)):
        point, match, mode, kw = rng.choice(FAULT_MENU)
        rules.append(faults.FaultRule(
            point=point, match=match, mode=mode,
            probability=rng.uniform(0.0005, 0.01),
            seed=rng.randrange(1 << 30), **kw,
        ))
    if corrupt:
        # poison-coverage variant: mangle source decodes; every record this
        # hits must be ACCOUNTED for (processing log or mutated sink row)
        rules.append(faults.FaultRule(
            point="serde.deserialize", match="JSON", mode="corrupt",
            probability=rng.uniform(0.01, 0.05),
            seed=rng.randrange(1 << 30),
        ))
    faults.install(rules)
    try:
        e = build_engine(backend)
        handle = list(e.queries.values())[0]
        topic = e.broker.topic(SRC_TOPIC)
        produced = set()
        next_id = 0
        t_end = time.time() + seconds
        faults_seen = 0
        stalls_seen = 0
        while time.time() < t_end:
            for _ in range(max(1, rate // 50)):
                rid = next_id
                next_id += 1
                try:
                    topic.produce(Record(
                        key=None, value=json.dumps({"ID": rid, "V": rid}),
                        timestamp=rid,
                    ))
                    produced.add(rid)
                except faults.FaultInjected:
                    pass  # producer-side loss: row never entered the log
            try:
                e.poll_once()
            except Exception as exc:  # noqa: BLE001 — nothing may escape
                return _result(False, f"poll_once leaked {type(exc).__name__}: {exc}",
                               e, handle, produced, verbose)
            if watch:
                # the /alerts view, polled embedded (same payload the REST
                # endpoint serves); recovery is asserted after convergence
                stalls_seen += sum(
                    1 for a in e.health_alerts() if a["health"] == "STALLED"
                )
            time.sleep(0.02 * rng.random())
        faults_seen = faults._INJECTOR.fired_total if faults._INJECTOR else 0
    finally:
        faults.clear()
    # convergence: no faults armed any more; drive to completion
    deadline = time.time() + 30
    while time.time() < deadline:
        e.poll_once()
        if handle.is_running() and handle.consumer.at_end():
            break
        time.sleep(0.005)
    got = set()
    for r in e.broker.topic("SOAK_OUT").all_records():
        got.add(json.loads(r.value)["ID"])
    lost = produced - got
    if watch:
        # any STALLED query still alerting after convergence (faults long
        # disarmed) is an un-recovered stall: self-healing failed
        unrecovered = [
            a["queryId"] for a in e.health_alerts()
            if a["health"] == "STALLED"
        ]
        if unrecovered:
            return _result(
                False,
                f"un-recovered STALLED after convergence: {unrecovered} "
                f"(stall alerts during soak: {stalls_seen})",
                e, handle, produced, verbose,
            )
    if corrupt:
        # no-silent-loss invariant: every missing row must be accounted for
        # by a poison/deserialize processing-log entry, or (corruption that
        # still parsed as JSON) by a sink row whose ID the producer never
        # wrote — nothing may vanish without a trace
        plog_errors = sum(
            1 for where, _m in e.processing_log
            if where.startswith("deserialize") or where.startswith("poison")
        )
        mutated = len(got - produced)
        silent = len(lost) - plog_errors - mutated
        ok = (silent <= 0 and handle.is_running() and not handle.terminal)
        msg = (f"produced={len(produced)} sunk={len(got & produced)} "
               f"poison_logged={plog_errors} mutated={mutated} "
               f"lost={len(lost)} silent_loss={max(silent, 0)} "
               f"faults_fired={faults_seen} restarts={handle.restart_count} "
               f"state={handle.state}")
        return _result(ok, msg, e, handle, produced, verbose)
    ok = (not lost and handle.is_running() and not handle.terminal)
    msg = (f"produced={len(produced)} sunk={len(got)} "
           f"dupes~={len(e.broker.topic('SOAK_OUT').all_records()) - len(got)} "
           f"faults_fired={faults_seen} restarts={handle.restart_count} "
           f"state={handle.state} lost={len(lost)}")
    return _result(ok, msg, e, handle, produced, verbose)


def hang_soak(seconds: float = 8.0, seed: int = 0, backend: str = "oracle",
              rate: int = 200, verbose: bool = True) -> dict:
    """Arm hang-mode faults inside ONE query's tick body under a tick
    deadline; assert deadline recovery and sibling isolation (see module
    docstring, ``--hang``)."""
    rng = random.Random(seed)
    e = KsqlEngine(KsqlConfig({
        cfg.RUNTIME_BACKEND: backend,
        cfg.QUERY_RETRY_BACKOFF_INITIAL_MS: 1,
        cfg.QUERY_RETRY_BACKOFF_MAX_MS: 20,
        cfg.QUERY_RETRY_MAX: 50,
        cfg.QUERY_TICK_TIMEOUT_MS: 100,
        cfg.HEALTH_STALL_TICKS: 5,
    }))
    e.execute_sql(
        "CREATE STREAM HV (ID BIGINT, V BIGINT) "
        "WITH (kafka_topic='hang_src', value_format='JSON');"
    )
    e.execute_sql("CREATE STREAM HV_OUT AS SELECT ID, V + 1 AS W FROM HV;")
    e.execute_sql(
        "CREATE STREAM SB (ID BIGINT, V BIGINT) "
        "WITH (kafka_topic='sib_src', value_format='JSON');"
    )
    e.execute_sql("CREATE STREAM SB_OUT AS SELECT ID, V + 2 AS W FROM SB;")
    victim = next(h for h in e.queries.values() if h.sink_name == "HV_OUT")
    sibling = next(h for h in e.queries.values() if h.sink_name == "SB_OUT")
    # a few deterministic hangs (4× the deadline) inside the victim's tick;
    # the sibling is never matched, so only the watchdog stands between the
    # hang and a cluster-wide stall
    rules = [
        faults.FaultRule(point=point, match=victim.query_id, mode="hang",
                         delay_ms=400.0, count=3,
                         after=rng.randint(0, 10),
                         seed=rng.randrange(1 << 30))
        for point in ("stage.process", "device.dispatch")
    ]
    faults.install(rules)
    vt = e.broker.topic("hang_src")
    sb = e.broker.topic("sib_src")
    sibling_advances = 0
    wm_at_first_deadline = None
    prev_sib = sum(sibling.consumer.positions.values())
    i = 0
    try:
        t_end = time.time() + seconds
        while time.time() < t_end:
            for _ in range(max(1, rate // 50)):
                vt.produce(Record(key=None,
                                  value=json.dumps({"ID": i, "V": i}),
                                  timestamp=i))
                sb.produce(Record(key=None,
                                  value=json.dumps({"ID": i, "V": i}),
                                  timestamp=i))
                i += 1
            try:
                e.poll_once()
            except Exception as exc:  # noqa: BLE001 — nothing may escape
                return _result(
                    False,
                    f"poll_once leaked {type(exc).__name__}: {exc}",
                    e, victim, set(range(i)), verbose,
                )
            sib_pos = sum(sibling.consumer.positions.values())
            wedged = victim.tick_deadlines and not (
                victim.is_running() and victim.consumer.at_end()
            )
            if wedged:
                if wm_at_first_deadline is None:
                    wm_at_first_deadline = sibling.progress.watermark_ms
                if sib_pos > prev_sib:
                    sibling_advances += 1
            prev_sib = sib_pos
            time.sleep(0.02 * rng.random())
    finally:
        faults.clear()
    # convergence: no faults armed; the victim must self-heal (or be
    # cleanly terminal) and the sibling must drain fully
    deadline = time.time() + 30
    while time.time() < deadline:
        e.poll_once()
        v_done = victim.terminal or (
            victim.is_running() and victim.consumer.at_end()
        )
        if v_done and sibling.is_running() and sibling.consumer.at_end():
            break
        time.sleep(0.005)
    retry_max = 50
    recovered = victim.is_running() and victim.consumer.at_end()
    terminal_ok = victim.terminal and victim.restart_count <= retry_max
    wm_now = sibling.progress.watermark_ms
    wm_advanced = (
        wm_at_first_deadline is None
        or (wm_now is not None and wm_now > wm_at_first_deadline)
    )
    ok = (
        victim.tick_deadlines >= 1
        and (recovered or terminal_ok)
        and sibling_advances >= 3
        and wm_advanced
        and sibling.is_running() and sibling.consumer.at_end()
    )
    msg = (f"deadlines={victim.tick_deadlines} "
           f"victim_state={victim.state} terminal={victim.terminal} "
           f"restarts={victim.restart_count} "
           f"replayed={victim.replayed_records} "
           f"sibling_advances_during_hang={sibling_advances} "
           f"sibling_watermark={wm_at_first_deadline}->{wm_now}")
    return _result(ok, msg, e, victim, set(range(i)), verbose)


def rescale_soak(seconds: float = 8.0, seed: int = 0, rate: int = 200,
                 verbose: bool = True) -> dict:
    """``--rescale``: force grow/shrink cutovers on distributed queries
    under the raise/delay/hang fault mix.  Two queries share the mesh: a
    stateless projection carries the no-lost-rows invariant and a COUNT
    aggregation carries reshard-restore state across every cutover; a push
    session rides the projection so gap markers across cutovers stay
    bounded.  Fails on lost rows, a terminal ERROR, an unbounded gap
    stream, or a soak that completed zero cutovers."""
    import tempfile

    rng = random.Random(seed)
    e = KsqlEngine(KsqlConfig({
        cfg.RUNTIME_BACKEND: "distributed",
        cfg.DEVICE_SHARDS: 2,
        cfg.BATCH_CAPACITY: 64,
        cfg.STATE_SLOTS: 1024,
        cfg.STATE_CHECKPOINT_DIR: tempfile.mkdtemp(prefix="soak-ckpt-"),
        cfg.QUERY_RETRY_BACKOFF_INITIAL_MS: 1,
        cfg.QUERY_RETRY_BACKOFF_MAX_MS: 20,
        cfg.QUERY_RETRY_MAX: 50,
        # sized ABOVE the cold XLA compile a post-cutover tick performs
        # (the config doc's sizing rule): a deadline below compile time
        # turns every rebuild into a deadline-kill loop
        cfg.QUERY_TICK_TIMEOUT_MS: 3000,
        cfg.HEALTH_STALL_TICKS: 5,
        cfg.DEVICE_SHARDS_MIN: 1,
        cfg.DEVICE_SHARDS_MAX: 4,
    }))
    e.execute_sql(
        f"CREATE STREAM SOAK (ID BIGINT, V BIGINT) "
        f"WITH (kafka_topic='{SRC_TOPIC}', value_format='JSON');"
    )
    e.execute_sql("CREATE STREAM SOAK_OUT AS SELECT ID, V * 3 AS W FROM SOAK;")
    e.execute_sql(
        "CREATE TABLE SOAK_AGG AS SELECT V % 8 AS K, COUNT(*) AS CNT "
        "FROM SOAK GROUP BY V % 8;"
    )
    handle = next(h for h in e.queries.values() if h.sink_name == "SOAK_OUT")
    agg = next(h for h in e.queries.values() if h.sink_name == "SOAK_AGG")
    from ksql_tpu.server.rest import PushQuerySession

    sess = PushQuerySession(e, "SELECT ID, W FROM SOAK_OUT EMIT CHANGES;")
    rules = []
    for _ in range(rng.randint(2, 3)):
        point, match, mode, kw = rng.choice(FAULT_MENU)
        rules.append(faults.FaultRule(
            point=point, match=match, mode=mode,
            probability=rng.uniform(0.0005, 0.005),
            seed=rng.randrange(1 << 30), **kw,
        ))
    # the hang leg of the mix: one wedged device dispatch in the
    # PROJECTION's tick mid-soak, contained by the tick deadline exactly
    # as in --hang (the cutover ladder must coexist with deadline kills)
    rules.append(faults.FaultRule(
        point="device.dispatch", match=handle.query_id, mode="hang",
        delay_ms=5000.0, count=1, after=rng.randint(5, 25),
        seed=rng.randrange(1 << 30),
    ))
    faults.install(rules)
    produced = set()
    next_id = 0
    directions = {}
    next_rescale = time.time() + 1.0
    try:
        return _rescale_soak_body(
            e, handle, agg, sess, rng, seconds, rate, produced, next_id,
            directions, next_rescale, verbose,
        )
    finally:
        # drain the supervision workers on EVERY exit path before
        # interpreter teardown: a daemon zombie killed mid-XLA-dispatch
        # aborts the whole process ('terminate called without an active
        # exception'), which would mask the verdict
        e.shutdown()


def _rescale_soak_body(e, handle, agg, sess, rng, seconds, rate, produced,
                       next_id, directions, next_rescale, verbose):
    try:
        topic = e.broker.topic(SRC_TOPIC)
        t_end = time.time() + seconds
        while time.time() < t_end:
            for _ in range(max(1, rate // 50)):
                rid = next_id
                next_id += 1
                try:
                    topic.produce(Record(
                        key=None, value=json.dumps({"ID": rid, "V": rid}),
                        timestamp=rid,
                    ))
                    produced.add(rid)
                except faults.FaultInjected:
                    pass  # producer-side loss: row never entered the log
            try:
                e.poll_once()
            except Exception as exc:  # noqa: BLE001 — nothing may escape
                return _result(
                    False, f"poll_once leaked {type(exc).__name__}: {exc}",
                    e, handle, produced, verbose,
                )
            try:
                sess.poll()
            except Exception:  # noqa: BLE001 — a dead session shows up in
                pass  # the gap/terminal accounting below
            if time.time() >= next_rescale:
                next_rescale = time.time() + 1.0
                for h in (handle, agg):
                    if not h.is_running() or h.pending_rescale is not None:
                        continue
                    dev = getattr(h.executor, "device", None)
                    cur = int(getattr(dev, "n_shards", 0) or 0)
                    if not cur:
                        continue
                    direction = directions.get(h.query_id, "grow")
                    target = min(cur * 2, 4) if direction == "grow" \
                        else max(cur // 2, 1)
                    if target != cur:
                        e._rescale_query(h, target, direction)
                    directions[h.query_id] = (
                        "shrink" if direction == "grow" else "grow"
                    )
            time.sleep(0.02 * rng.random())
        faults_seen = faults._INJECTOR.fired_total if faults._INJECTOR else 0
    finally:
        faults.clear()
    # convergence: both queries drain with no faults armed
    deadline = time.time() + 60
    while time.time() < deadline:
        e.poll_once()
        try:
            sess.poll()
        except Exception:  # noqa: BLE001
            pass
        done = all(
            h.is_running() and h.consumer.at_end() for h in (handle, agg)
        )
        if done:
            break
        time.sleep(0.005)
    got = set()
    for r in e.broker.topic("SOAK_OUT").all_records():
        got.add(json.loads(r.value)["ID"])
    lost = produced - got
    cutovers = {
        "projection": dict(handle.reshard_total),
        "aggregate": dict(agg.reshard_total),
    }
    n_cut = sum(sum(d.values()) for d in cutovers.values())
    gaps = sum(1 for row in sess.rows if "__gap__" in row)
    # bounded gap markers per push session: each incident (session restart
    # or engine cutover the session observed) emits at most one marker
    gap_bound = sess.restart_count + n_cut + 5
    ok = (
        not lost
        and handle.is_running() and not handle.terminal
        and agg.is_running() and not agg.terminal
        and n_cut >= 2
        and gaps <= gap_bound
    )
    msg = (f"produced={len(produced)} sunk={len(got)} lost={len(lost)} "
           f"cutovers={cutovers} faults_fired={faults_seen} "
           f"restarts={handle.restart_count}/{agg.restart_count} "
           f"gaps={gaps} (bound {gap_bound}) "
           f"shards_now={getattr(getattr(agg.executor, 'device', None), 'n_shards', '?')} "
           f"states={handle.state}/{agg.state}")
    return _result(ok, msg, e, handle, produced, verbose)


def mesh_soak(seconds: float = 10.0, seed: int = 0, rate: int = 200,
              verbose: bool = True) -> dict:
    """``--mesh``: the shard-level fault domain under adversarial load
    (ISSUE 14).  Three carriers run ``backend=distributed`` on a 2-shard
    mesh — a projection (no-lost-rows carrier), a windowed COUNT
    aggregation (degraded-mesh cutover carrier: its state crosses the
    cutover through reshard-restore), and a stream-stream join — while
    randomized mesh faults (``mesh.encode`` / ``mesh.exchange`` raises,
    whole-mesh ``device.dispatch`` kills) fire, plus ONE targeted
    single-shard hang: ``mesh.shard.dispatch`` wedges the aggregation's
    shard-1 dispatch lane past the tick deadline until the strike
    threshold triggers a degraded-mesh cutover.

    Invariants: zero lost projection rows, >= 1 completed degraded-mesh
    cutover on the aggregation, no carrier ends terminal, and the final
    sink + pull state of every carrier is identical to a fault-free
    oracle twin fed the same records."""
    import tempfile

    rng = random.Random(seed)
    e = KsqlEngine(KsqlConfig({
        cfg.RUNTIME_BACKEND: "distributed",
        cfg.DEVICE_SHARDS: 2,
        cfg.BATCH_CAPACITY: 64,
        cfg.STATE_SLOTS: 1024,
        cfg.STATE_CHECKPOINT_DIR: tempfile.mkdtemp(prefix="mesh-ckpt-"),
        cfg.QUERY_RETRY_BACKOFF_INITIAL_MS: 1,
        cfg.QUERY_RETRY_BACKOFF_MAX_MS: 20,
        cfg.QUERY_RETRY_MAX: 50,
        cfg.HEALTH_STALL_TICKS: 5,
        cfg.MESH_FAIL_THRESHOLD: 2,
        # deterministic deadline math below (tick deadline vs the targeted
        # hang): auto-raising the knob mid-soak would stretch the waits
        cfg.DEADLINE_AUTOSIZE: False,
        # regrow probe short enough that a post-soak drain may restore
        # the original width (not asserted: chaos may legitimately leave
        # the mesh degraded; the parity invariants hold either way)
        cfg.MESH_REGROW_COOLDOWN_MS: 5000,
    }))
    ddls = [
        f"CREATE STREAM SOAK (ID BIGINT, V BIGINT) "
        f"WITH (kafka_topic='{SRC_TOPIC}', value_format='JSON');",
        "CREATE STREAM SIDE (ID BIGINT, B BIGINT) "
        "WITH (kafka_topic='soak_side', value_format='JSON');",
    ]
    queries = [
        "CREATE STREAM SOAK_OUT AS SELECT ID, V * 3 AS W FROM SOAK;",
        "CREATE TABLE SOAK_AGG AS SELECT V % 8 AS K, COUNT(*) AS CNT "
        "FROM SOAK WINDOW TUMBLING (SIZE 1 HOUR) GROUP BY V % 8;",
        "CREATE STREAM SOAK_J AS SELECT SOAK.ID, SOAK.V, SIDE.B FROM SOAK "
        "JOIN SIDE WITHIN 1 HOUR ON SOAK.ID = SIDE.ID;",
    ]
    for stmt in ddls + queries:
        e.execute_sql(stmt)
    by_sink = {h.sink_name: h for h in e.queries.values()}
    proj = by_sink["SOAK_OUT"]
    agg = by_sink["SOAK_AGG"]
    join = by_sink["SOAK_J"]
    carriers = [proj, agg, join]
    assert all(h.backend == "distributed" for h in carriers), (
        "mesh soak carriers must run distributed: "
        + str({h.sink_name: h.backend for h in carriers})
    )
    produced = set()
    next_id = 0
    topic = e.broker.topic(SRC_TOPIC)
    side = e.broker.topic("soak_side")

    def produce_round():
        nonlocal next_id
        for _ in range(max(1, rate // 50)):
            rid = next_id
            next_id += 1
            try:
                topic.produce(Record(
                    key=None, value=json.dumps({"ID": rid, "V": rid}),
                    timestamp=rid,
                ))
                produced.add(rid)
            except faults.FaultInjected:
                pass  # producer-side loss: row never entered the log
            if rid % 4 == 0:
                try:
                    side.produce(Record(
                        key=None,
                        value=json.dumps({"ID": rid, "B": rid * 2}),
                        timestamp=rid,
                    ))
                except faults.FaultInjected:
                    pass

    # WARMUP, fault-free and deadline-free: every carrier pays its cold
    # XLA compile here (a tick deadline armed below cold-compile time
    # would deadline-kill the ss-join's very first tick and lose its
    # arrival-ordered ring state — the documented sizing footgun, not the
    # fault domain under test), then a checkpoint gives the aggregation a
    # restorable commit point for the degraded-mesh cutover
    for _ in range(3):
        produce_round()
    e.run_until_quiescent()
    e.checkpoint()
    # steady state compiled: arm the tick deadline the targeted hang must
    # blow (hang >> deadline, so the watchdog — not the fault expiring —
    # is what recovers, and the wedged lane is attributable)
    e.session_properties[cfg.QUERY_TICK_TIMEOUT_MS] = 5000
    rules = []
    # randomized whole-mesh chaos: encode/exchange/dispatch raises take
    # the ordinary restart ladder (never shard strikes)
    menu = [
        ("mesh.encode", "", "raise", {}),
        ("mesh.exchange", "", "raise", {}),
        ("device.dispatch", "", "raise", {}),
        ("topic.read", SRC_TOPIC, "raise", {}),
    ]
    for _ in range(rng.randint(2, 3)):
        point, match, mode, kw = rng.choice(menu)
        rules.append(faults.FaultRule(
            point=point, match=match, mode=mode,
            probability=rng.uniform(0.0005, 0.005),
            seed=rng.randrange(1 << 30), **kw,
        ))
    # the tentpole seam: ONE targeted single-shard hang — shard 1 of the
    # aggregation wedges FAR past the tick deadline, twice (= the strike
    # threshold), forcing a degraded-mesh cutover
    rules.append(faults.FaultRule(
        point="mesh.shard.dispatch", match=f"{agg.query_id}#1#",
        mode="hang", delay_ms=90000.0, count=2,
        after=rng.randint(1, 5), seed=rng.randrange(1 << 30),
    ))
    faults.install(rules)
    # the abandoned hang workers sleep up to 90s: EVERY exit path —
    # including an early failure return from the soak loop — must pass
    # through shutdown()'s bounded join, or a daemon zombie killed
    # mid-XLA-dispatch aborts the interpreter and masks the verdict
    try:
        try:
            t_end = time.time() + seconds
            # the two deadline waits alone cost ~10s: keep soaking past
            # the nominal budget until the targeted hang's cutover
            # completed (or a hard cap — a missing cutover then FAILS
            # the invariant)
            hard_end = time.time() + max(3 * seconds, seconds + 45)
            while time.time() < t_end or (
                time.time() < hard_end
                and not agg.reshard_total.get("degrade")
            ):
                produce_round()
                try:
                    e.poll_once()
                except Exception as exc:  # noqa: BLE001 — nothing may
                    return _result(  # escape
                        False,
                        f"poll_once leaked {type(exc).__name__}: {exc}",
                        e, agg, produced, verbose,
                    )
                time.sleep(0.02 * rng.random())
            faults_seen = (
                faults._INJECTOR.fired_total if faults._INJECTOR else 0
            )
        finally:
            faults.clear()
        # convergence: all carriers drain with no faults armed
        deadline = time.time() + 60
        while time.time() < deadline:
            e.poll_once()
            if all(
                h.is_running() and h.consumer.at_end() for h in carriers
            ):
                break
            time.sleep(0.005)
        # fault-free oracle twin: same statements, same records, no chaos
        eo = KsqlEngine(KsqlConfig({cfg.RUNTIME_BACKEND: "oracle"}))
        for stmt in ddls + queries:
            eo.execute_sql(stmt)
        for r in e.broker.topic(SRC_TOPIC).all_records():
            eo.broker.topic(SRC_TOPIC).produce(Record(
                key=None, value=r.value, timestamp=r.timestamp))
        for r in e.broker.topic("soak_side").all_records():
            eo.broker.topic("soak_side").produce(Record(
                key=None, value=r.value, timestamp=r.timestamp))
        eo.run_until_quiescent()

        def sink_set(engine, sink):
            return {
                r.value for r in engine.broker.topic(sink).all_records()
            }

        def pull_agg(engine):
            res = engine.execute_sql("SELECT K, CNT FROM SOAK_AGG;")
            return sorted(
                repr(sorted(r.items())) for r in res[0].rows
            )

        got = set()
        for r in e.broker.topic("SOAK_OUT").all_records():
            got.add(json.loads(r.value)["ID"])
        lost = produced - got
        problems = []
        if lost:
            problems.append(f"{len(lost)} projection rows lost")
        degrades = agg.reshard_total.get("degrade", 0)
        if degrades < 1:
            problems.append(
                "targeted single-shard hang produced no degraded-mesh "
                f"cutover (reshard_total={dict(agg.reshard_total)})"
            )
        for h in carriers:
            if h.terminal or not h.is_running():
                problems.append(
                    f"{h.sink_name} ended {h.state} terminal={h.terminal}"
                )
        # final-state parity vs the fault-free twin: sink row SETS (the
        # at-least-once replay window may duplicate, never lose or
        # corrupt) and the aggregation's pull-query state
        for sink in ("SOAK_OUT", "SOAK_J"):
            if sink_set(e, sink) != sink_set(eo, sink):
                problems.append(f"{sink} sink diverged from oracle twin")
        if pull_agg(e) != pull_agg(eo):
            problems.append("SOAK_AGG pull state diverged from oracle twin")
        strikes = dict(agg.shard_strikes_total)
        ok = not problems
        msg = (
            f"produced={len(produced)} sunk={len(got)} lost={len(lost)} "
            f"strikes={strikes} degrades={degrades} "
            f"reshard={dict(agg.reshard_total)} "
            f"shards_now={getattr(getattr(agg.executor, 'device', None), 'n_shards', '?')} "
            f"deadlines={agg.tick_deadlines} faults_fired={faults_seen} "
            f"restarts={proj.restart_count}/{agg.restart_count}/"
            f"{join.restart_count}"
        )
        if problems:
            msg += " | " + "; ".join(problems)
        return _result(ok, msg, e, agg, produced, verbose)
    finally:
        e.shutdown()


def _timeline_coverage(e):
    """ISSUE 18 closing-the-loop invariant, asserted post-run by EVERY
    mode (all verdicts funnel through _result): each annotation-worthy
    incident category the soak drove into the processing log — cutovers,
    degrades, deadline kills, overload engage/clear, skew verdicts —
    must be visible as a retained timeline annotation.  Chaos events the
    timeline cannot show an operator are chaos events that never
    happened, observability-wise.  Returns an error string or None."""
    from ksql_tpu.common import timeline as tlm

    if not getattr(e, "telemetry_enabled", False):
        return None
    want = set()
    for where, _msg in e.processing_log:
        cat = tlm.plog_category(where)
        if cat in tlm.ANNOTATION_CATEGORIES:
            want.add(cat)
    if not want:
        return None
    seen = set()
    for tl in e.timelines.values():
        seen.update(tl.annotation_kinds())
    missing = sorted(want - seen)
    if missing:
        return (
            f"incident categories in the processing log but missing from "
            f"every retained timeline: {missing}"
        )
    return None


def _result(ok, msg, e, handle, produced, verbose):
    tl_err = _timeline_coverage(e)
    if tl_err:
        ok = False
        msg = f"{msg} | {tl_err}"
    out = {"ok": ok, "message": msg,
           "state": handle.state, "terminal": handle.terminal,
           "restarts": handle.restart_count, "produced": len(produced)}
    if verbose:
        print(("PASS " if ok else "FAIL ") + msg)
    return out


def fanout_soak(seconds: float = 8.0, seed: int = 0, rate: int = 200,
                taps: int = 50, verbose: bool = True,
                fused: bool = True) -> dict:
    """``--fanout``: kill/hang the ONE shared push-registry pipeline under
    ~50 filtered taps — once with the fused residual kernel enabled and
    once disabled (main() runs both).  Asserts: exactly one pipeline
    served every tap the whole soak, no tap ended terminal within the
    retry budget, at least one heal happened, and no rows were lost
    beyond gap-marked spans (per-tap shortfall implies that tap saw an
    eviction gap, and the global shortfall is bounded by the registry's
    ring-evicted count).  With ``fused`` a ``push.residual.kernel`` fault
    additionally fires mid-soak and the soak asserts the degrade
    contract: ONE plog entry, pipeline drops to host residuals, delivery
    continues — never a terminal tap."""
    from ksql_tpu.server.rest import PushQuerySession

    rng = random.Random(seed)
    e = KsqlEngine(KsqlConfig({
        cfg.RUNTIME_BACKEND: "oracle",
        cfg.QUERY_RETRY_BACKOFF_INITIAL_MS: 1,
        cfg.QUERY_RETRY_BACKOFF_MAX_MS: 20,
        cfg.QUERY_RETRY_MAX: 50,
        cfg.PUSH_FUSED_ENABLE: fused,
        # small ring so a genuinely slow tap exercises the eviction-gap
        # contract under load
        cfg.PUSH_REGISTRY_RING_SIZE: 512,
    }))
    e.execute_sql(
        f"CREATE STREAM SOAK (ID BIGINT, V BIGINT) "
        f"WITH (kafka_topic='{SRC_TOPIC}', value_format='JSON');"
    )
    e.session_properties["auto.offset.reset"] = "latest"
    mods = [2, 3, 4, 5]
    specs = [(mods[i % len(mods)], i % mods[i % len(mods)])
             for i in range(taps)]
    sessions = [
        PushQuerySession(
            e, f"SELECT ID, V FROM SOAK WHERE V % {m} = {r} EMIT CHANGES;"
        )
        for m, r in specs
    ]
    reg = e.push_registry
    rules = [
        # the tentpole seam: kill the SHARED pipeline mid-soak, repeatedly
        faults.FaultRule(point="push.pipeline.step", mode="raise",
                         probability=0.005, seed=rng.randrange(1 << 30)),
        # ...and wedge it briefly (a short hang models a stalled advance
        # that delays every tap without killing any)
        faults.FaultRule(point="push.pipeline.step", mode="hang",
                         delay_ms=100.0, count=2, after=rng.randint(5, 20),
                         seed=rng.randrange(1 << 30)),
        faults.FaultRule(point="topic.read", match=SRC_TOPIC, mode="raise",
                         probability=0.01, seed=rng.randrange(1 << 30)),
    ]
    if fused:
        # the ISSUE-12 seam: fail the fused residual kernel once mid-soak
        # — must degrade THAT pipeline to host residuals with one plog
        # entry, never a terminal tap
        rules.append(faults.FaultRule(
            point="push.residual.kernel", mode="raise", count=1,
            after=rng.randint(3, 10), seed=rng.randrange(1 << 30),
        ))
    faults.install(rules)
    produced = []
    delivered = [[] for _ in sessions]
    gaps = [[] for _ in sessions]
    next_id = 0
    try:
        topic = e.broker.topic(SRC_TOPIC)
        t_end = time.time() + seconds
        while time.time() < t_end:
            for _ in range(max(1, int(rate / 50))):
                row = {"ID": next_id, "V": next_id}
                try:
                    topic.produce(Record(
                        key=None, value=json.dumps(row), timestamp=next_id
                    ))
                    produced.append(next_id)
                except Exception:
                    pass  # producer-side loss: excluded from expectation
                next_id += 1
            for i, s in enumerate(sessions):
                for r in s.poll():
                    if "__gap__" in r:
                        gaps[i].append(r["__gap__"])
                    else:
                        delivered[i].append(r["V"])
            time.sleep(0.02)
        faults.install([])  # convergence: drain with faults disarmed
        for _ in range(80):
            quiet = True
            for i, s in enumerate(sessions):
                rows = s.poll()
                for r in rows:
                    if "__gap__" in r:
                        gaps[i].append(r["__gap__"])
                    else:
                        delivered[i].append(r["V"])
                quiet = quiet and not rows
            if quiet and reg.stats()["pipeline-detail"].get(
                "SOAK", {}
            ).get("restarts", 0) == 0:
                break
            time.sleep(0.005)  # outwait a heal backoff mid-drain
        stats = reg.stats()
        lost_total = 0
        problems = []
        for i, ((m, r), got, gp) in enumerate(zip(specs, delivered, gaps)):
            expect = [v for v in produced if v % m == r]
            missing = set(expect) - set(got)
            # per-tap invariant: every loss must be covered by that tap's
            # OWN gap-marked spans (an eviction marker's skippedRows counts
            # the rows that tap skipped — predicate-matching or not — so
            # missing ⊆ skipped always holds when the contract does)
            skipped = sum(g.get("skippedRows", 0) for g in gp
                          if g.get("evicted"))
            lost_total += len(missing)
            if len(missing) > skipped:
                problems.append(
                    f"tap {i} lost {len(missing)} rows beyond its "
                    f"gap-marked spans ({skipped} skipped rows marked)"
                )
            if sessions[i].terminal:
                problems.append(f"tap {i} ended terminal")
        if stats["pipelines"] != 1:
            problems.append(f"{stats['pipelines']} pipelines, want 1")
        if stats["taps-total"] != taps:
            problems.append(f"{stats['taps-total']} taps, want {taps}")
        degrades = [w for w, _ in e.processing_log
                    if w.startswith("push.residual.degrade:")]
        if fused:
            # degrade contract: the injected kernel failure produced
            # exactly ONE plog entry and flipped the pipeline to host
            # residuals — it never killed a tap (checked above) and
            # never fired twice
            if len(degrades) != 1:
                problems.append(
                    f"{len(degrades)} push.residual.degrade plog entries, "
                    "want exactly 1"
                )
            if stats["residual"]["degraded-total"] != 1:
                problems.append(
                    f"residual degraded-total="
                    f"{stats['residual']['degraded-total']}, want 1"
                )
        elif degrades:
            problems.append(
                "fused kernel disabled but push.residual.degrade fired"
            )
        heals = stats["heals-total"]
        tl_err = _timeline_coverage(e)
        if tl_err:
            problems.append(tl_err)
        ok = not problems
        msg = (
            f"fused={fused} produced={len(produced)} taps={taps} "
            f"heals={heals} "
            f"evicted={stats['ring-evicted-total']} "
            f"gap-markers={stats['gap-markers-total']} "
            f"degrades={len(degrades)} "
            f"lost-within-gaps={lost_total}"
        )
        if problems:
            msg += " | " + "; ".join(problems)
        if verbose:
            print(("OK " if ok else "FAIL ") + msg)
        return {"ok": ok, "message": msg, "heals": heals,
                "produced": len(produced), "lost": lost_total}
    finally:
        e.shutdown()


def overload_soak(seconds: float = 6.0, seed: int = 0, rate: int = 300,
                  taps: int = 12, verbose: bool = True) -> dict:
    """``--overload``: producer flood + tap storm + transient-query storm
    against a live ``KsqlServer`` under a tight HBM budget and aggressive
    overload thresholds (see the module docstring for the invariant
    list)."""
    import threading
    import urllib.error
    import urllib.request

    from ksql_tpu.server.rest import KsqlServer

    rng = random.Random(seed)
    e = KsqlEngine(KsqlConfig({
        cfg.RUNTIME_BACKEND: "device",
        cfg.BATCH_CAPACITY: 64,
        cfg.STATE_SLOTS: 512,
        # tight HBM budget: the graftmem admission gate still admits the
        # two carriers, and the monitor's hbm resource samples live
        # device_state_bytes() against it every tick
        cfg.MEMORY_BUDGET_BYTES: 32 << 20,
        cfg.OVERLOAD_INTERVAL_MS: 50,
        cfg.OVERLOAD_HYSTERESIS_TICKS: 2,
        cfg.OVERLOAD_LAG_ELEVATED_ROWS: 200,
        cfg.OVERLOAD_LAG_CRITICAL_ROWS: 1000,
        cfg.OVERLOAD_MAX_INFLIGHT: 4,
        # above the opening burst a POLLED tap can transiently carry, so
        # only the starved taps (whose lag grows with total production)
        # cross it
        cfg.OVERLOAD_TAP_LAG_BOUND: 3000,
        cfg.OVERLOAD_RETRY_AFTER_S: 1,
        cfg.PUSH_REGISTRY_RING_SIZE: 512,
        cfg.QUERY_RETRY_BACKOFF_INITIAL_MS: 1,
        cfg.QUERY_RETRY_BACKOFF_MAX_MS: 20,
        cfg.HEALTH_STALL_TICKS: 5,
    }))
    server = KsqlServer(engine=e, port=0)
    server.start()
    ov = e.overload
    ddl = (
        f"CREATE STREAM SOAK (ID BIGINT, V BIGINT) "
        f"WITH (kafka_topic='{SRC_TOPIC}', value_format='JSON');"
    )
    queries = [
        "CREATE STREAM SOAK_HI AS SELECT ID, V * 3 AS W FROM SOAK;",
        "CREATE STREAM SOAK_LO AS SELECT ID, V + 1 AS W FROM SOAK;",
    ]

    def post(path, body, timeout=30.0):
        req = urllib.request.Request(
            server.url + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status
        except urllib.error.HTTPError as err:
            err.read()
            return err.code

    problems = []
    shed_429 = 0
    ok_200 = 0
    hung = 0
    storm_stop = threading.Event()

    def transient_storm():
        nonlocal shed_429, ok_200, hung
        while not storm_stop.is_set():
            try:
                code = post("/query", {"ksql": "SELECT * FROM SOAK_HI;"},
                            timeout=30.0)
            except Exception:  # noqa: BLE001 — a timeout IS the hang the
                hung += 1      # 429 contract forbids
                continue
            if code == 429:
                shed_429 += 1
            elif code == 200:
                ok_200 += 1
            storm_stop.wait(0.05)

    try:
        assert post("/ksql", {"ksql": ddl}) == 200
        # different ksql.query.priority per carrier: under source-pacing
        # the low-priority query sheds device work first
        assert post("/ksql", {
            "ksql": queries[0],
            "streamsProperties": {cfg.QUERY_PRIORITY: 200},
        }) == 200
        assert post("/ksql", {
            "ksql": queries[1],
            "streamsProperties": {cfg.QUERY_PRIORITY: 10},
        }) == 200
        with server.engine_lock:
            by_sink = {h.sink_name: h for h in e.queries.values()}
        hi, lo = by_sink["SOAK_HI"], by_sink["SOAK_LO"]
        if hi.priority != 200 or lo.priority != 10:
            problems.append(
                f"priorities not captured: hi={hi.priority} lo={lo.priority}"
            )
        # tap storm: half the taps are polled, half deliberately NEVER
        # polled — their lag must trip the overload shed, not stall
        with server.engine_lock:
            e.session_properties["auto.offset.reset"] = "latest"
            tap_sessions = [
                server.open_push_query(
                    f"SELECT ID, V FROM SOAK WHERE V % 2 = {i % 2} "
                    "EMIT CHANGES;"
                )
                for i in range(taps)
            ]
        polled = tap_sessions[: taps // 2]
        starved = tap_sessions[taps // 2:]
        # injected monitor faults: each raise must be absorbed (one plog
        # entry, sampling continues) — never kill the monitor thread
        faults.install([faults.FaultRule(
            point="overload.monitor", mode="raise", count=3,
            after=rng.randint(3, 8), seed=rng.randrange(1 << 30),
        )])
        storm = threading.Thread(target=transient_storm, daemon=True)
        storm.start()
        topic = e.broker.topic(SRC_TOPIC)
        produced = 0

        def produce_burst(n):
            nonlocal produced
            for _ in range(n):
                topic.produce(Record(
                    key=None,
                    value=json.dumps({"ID": produced, "V": produced}),
                    timestamp=produced,
                ))
                produced += 1

        # the flood: an opening burst blows the lag thresholds instantly,
        # then sustained production keeps pressure up for the duration
        produce_burst(4000)
        t_end = time.time() + seconds
        max_engaged = 0
        mid_ddl_code = None
        while time.time() < t_end:
            produce_burst(max(1, rate // 50))
            for s in polled:
                server.poll_push_query(s)
            st = ov.stats()
            max_engaged = max(max_engaged, sum(st["engaged"].values()))
            if mid_ddl_code is None and st["engaged"]["admission"]:
                # persistent DDL must stay accepted while transient
                # queries are being shed
                mid_ddl_code = post("/ksql", {
                    "ksql": "CREATE STREAM EXTRA (ID BIGINT) WITH ("
                            "kafka_topic='extra', value_format='JSON');",
                })
            time.sleep(0.02)
        faults.clear()
        storm_stop.set()
        storm.join(timeout=60)
        # drain: the flood is over — every action must clear and both
        # carriers must catch up (source pacing releases as lag drops)
        deadline = time.time() + 120
        cleared = False
        while time.time() < deadline:
            for s in polled:
                server.poll_push_query(s)
            st = ov.stats()
            with server.engine_lock:
                caught_up = all(
                    h.is_running() and h.consumer.at_end()
                    for h in (hi, lo)
                )
            if caught_up and not any(st["engaged"].values()):
                cleared = True
                break
            time.sleep(0.05)
        stats = ov.stats()
        # ---- invariants
        if max_engaged < 1 or sum(stats["actions-total"].values()) < 1:
            problems.append("no degradation action ever engaged")
        if not cleared:
            problems.append(
                f"actions still engaged after the flood drained: "
                f"{stats['engaged']} (level={stats['level']})"
            )
        if shed_429 < 1:
            problems.append("transient-query storm saw no 429 sheds")
        if hung:
            problems.append(f"{hung} transient requests hung (no reply "
                            "within timeout) — the 429 contract forbids it")
        if mid_ddl_code != 200:
            problems.append(
                f"mid-flood persistent DDL got {mid_ddl_code}, want 200"
            )
        if stats["monitor-errors-total"] < 1:
            problems.append("injected overload.monitor faults never fired")
        shed_taps = [s for s in starved if s.terminal]
        overload_marked = [
            s for s in shed_taps
            if any(
                r["__gap__"].get("overload")
                for r in s.rows if "__gap__" in r
            )
        ]
        if not shed_taps:
            problems.append("no starved tap was disconnected by the "
                            "overload shed")
        elif not overload_marked:
            problems.append("shed taps carry no terminal gap marker "
                            "naming overload")
        with server.engine_lock:
            for h in (hi, lo):
                if h.terminal or not h.is_running():
                    problems.append(
                        f"{h.sink_name} ended {h.state} "
                        f"terminal={h.terminal}"
                    )
        # process alive: the server still answers
        try:
            with urllib.request.urlopen(
                server.url + "/healthcheck", timeout=10
            ) as r:
                json.loads(r.read())
        except Exception as err:  # noqa: BLE001
            problems.append(f"/healthcheck unreachable post-flood: {err}")
        # persistent-sink parity vs a fault-free oracle twin fed the same
        # records: overload sheds REQUESTS and taps, never sink rows
        eo = KsqlEngine(KsqlConfig({cfg.RUNTIME_BACKEND: "oracle"}))
        try:
            for stmt in [ddl] + queries:
                eo.execute_sql(stmt)
            for r in e.broker.topic(SRC_TOPIC).all_records():
                eo.broker.topic(SRC_TOPIC).produce(Record(
                    key=None, value=r.value, timestamp=r.timestamp))
            eo.run_until_quiescent()
            for sink in ("SOAK_HI", "SOAK_LO"):
                mine = {r.value for r in e.broker.topic(sink).all_records()}
                ref = {r.value for r in eo.broker.topic(sink).all_records()}
                if mine != ref:
                    problems.append(
                        f"{sink} diverged from the fault-free twin "
                        f"(got {len(mine)} distinct rows, want {len(ref)})"
                    )
        finally:
            eo.shutdown()
        with server.engine_lock:
            tl_err = _timeline_coverage(e)
        if tl_err:
            problems.append(tl_err)
        ok = not problems
        msg = (
            f"produced={produced} sheds_429={shed_429} served_200={ok_200} "
            f"actions={dict(stats['actions-total'])} "
            f"taps_shed={stats['taps-disconnected-total']} "
            f"monitor_errors={stats['monitor-errors-total']} "
            f"samples={stats['samples-total']}"
        )
        if problems:
            msg += " | " + "; ".join(problems)
        if verbose:
            print(("PASS " if ok else "FAIL ") + f"seed={seed} " + msg)
        return {"ok": ok, "message": msg, "sheds": shed_429,
                "produced": produced}
    finally:
        faults.clear()
        storm_stop.set()
        server.stop()


# ------------------------------------------------- kill -9 crash soak
#
# ``--crash`` (ISSUE 20): a REAL KsqlServer subprocess runs stateful
# carriers (windowed GROUP BY + stream-stream join) over a WAL + a
# checkpoint dir + the incremental changelog journal, and the harness
# SIGKILLs it at randomized points: mid-tick, mid-checkpoint-save
# (env-armed ``checkpoint.save:hang``), and mid-changelog-append
# (env-armed ``changelog.append:hang`` — the hang sits BETWEEN the
# frame's header and payload writes, so the kill leaves a genuinely
# torn tail frame on disk).  Every restart reuses the same dirs;
# restart configs carry NO fault rules, so a schedule never re-arms.
# The final (clean) round drains, checkpoints, and dumps every topic;
# parity runs against a crash-free in-process oracle twin fed the
# dumped source records.
#
# Invariants: zero ACKed-then-lost rows (every acknowledged INSERT is
# in the dumped source topics and every twin sink row is in ours),
# duplicates bounded by one in-flight tick per crash, the measured
# recovery replay window (ksql_query_recovery_replayed_rows_total,
# scraped from /metrics at each restart) stays ticks-since-last-
# checkpoint — never the whole batch — and the mid-append kill left a
# torn tail the next recovery truncated away.

CRASH_SOURCES = [
    "CREATE STREAM PV (URL STRING, UID BIGINT) "
    "WITH (kafka_topic='crash_pv', value_format='JSON');",
    "CREATE STREAM CK (URL STRING, CODE BIGINT) "
    "WITH (kafka_topic='crash_ck', value_format='JSON');",
]
CRASH_CARRIERS = [
    "CREATE TABLE CRASH_AGG AS SELECT URL, COUNT(*) AS CNT, "
    "SUM(UID) AS S FROM PV WINDOW TUMBLING (SIZE 4 SECONDS) "
    "GROUP BY URL EMIT CHANGES;",
    "CREATE STREAM CRASH_JO AS SELECT P.URL AS URL, P.UID AS UID, "
    "C.CODE AS CODE FROM PV P JOIN CK C WITHIN 20 SECONDS "
    "ON P.URL = C.URL EMIT CHANGES;",
]
CRASH_SINKS = ("CRASH_AGG", "CRASH_JO")
CRASH_SRC_TOPICS = ("crash_pv", "crash_ck")


def crash_serve() -> int:
    """``--serve``: the crash-soak child process.  Boots a KsqlServer
    from the JSON spec in $KSQL_CHAOS_SERVE (config incl. any env-armed
    fault rules, WAL path, port file, dump file), serves until SIGTERM,
    then drains, stops cleanly (final checkpoint) and dumps every topic
    + the processing log for the parity check.  A SIGKILL mid-anything
    is the intended death."""
    import signal
    import threading

    from ksql_tpu.server.rest import KsqlServer

    spec = json.loads(os.environ["KSQL_CHAOS_SERVE"])
    e = KsqlEngine(KsqlConfig(spec["config"]))
    server = KsqlServer(
        engine=e, command_log_path=spec["command_log"], port=0,
    )
    stop_evt = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop_evt.set())
    server.start()
    tmp = spec["port_file"] + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(server.port))
    os.replace(tmp, spec["port_file"])  # atomic: readers never see ""
    while not stop_evt.wait(0.05):
        pass
    # drain before the clean-shutdown snapshot: the parity dump must
    # reflect every WAL row the harness acknowledged
    deadline = time.time() + 60
    while time.time() < deadline:
        with server.engine_lock:
            done = all(
                h.consumer.at_end()
                for h in e.queries.values() if h.is_running()
            )
        if done:
            break
        time.sleep(0.05)
    server.stop()
    dump = {
        "plog": [[k, str(m)] for k, m in e.processing_log],
        "topics": {},
    }
    for name in e.broker.list_topics():
        dump["topics"][name] = [
            [r.key, r.value, r.timestamp,
             list(r.window) if r.window else None]
            for r in e.broker.topic(name).all_records()
        ]
    tmp = spec["dump_file"] + ".tmp"
    with open(tmp, "w") as f:
        json.dump(dump, f, default=str)
    os.replace(tmp, spec["dump_file"])
    return 0


def run_crash(seconds: float = 10.0, seed: int = 0, rate: int = 200,
              verbose: bool = True) -> dict:
    """``--crash``: SIGKILL a live KsqlServer subprocess at randomized
    points across three kill classes, restart it on the same dirs, and
    assert effectively-once sink parity vs a crash-free oracle twin
    (see the section comment above for the invariant list)."""
    import shutil
    import signal
    import subprocess
    import tempfile
    import urllib.error
    import urllib.request

    from ksql_tpu.runtime.changelog import read_frames

    rng = random.Random(seed)
    work = tempfile.mkdtemp(prefix=f"crash_soak_{seed}_")
    ckpt = os.path.join(work, "ckpt")
    wal = os.path.join(work, "commands.jsonl")
    dump_file = os.path.join(work, "dump.json")
    base_config = {
        cfg.RUNTIME_BACKEND: "oracle",
        cfg.STATE_CHECKPOINT_DIR: ckpt,
        cfg.CHECKPOINT_INTERVAL_MS: 250,
        cfg.QUERY_RETRY_BACKOFF_INITIAL_MS: 1,
        cfg.QUERY_RETRY_BACKOFF_MAX_MS: 20,
    }
    problems: list = []
    acked: list = []  # (topic, rowtime) per 200-acknowledged INSERT
    ts_clock = [0]
    urls = ["/a", "/b", "/c", "/d"]

    def next_inserts(n):
        out = []
        for _ in range(n):
            ts_clock[0] += 1000
            u = rng.choice(urls)
            if rng.random() < 0.35:
                out.append((
                    "crash_ck", ts_clock[0],
                    f"INSERT INTO CK (ROWTIME, URL, CODE) VALUES "
                    f"({ts_clock[0]}, '{u}', {rng.randrange(100)});",
                ))
            else:
                out.append((
                    "crash_pv", ts_clock[0],
                    f"INSERT INTO PV (ROWTIME, URL, UID) VALUES "
                    f"({ts_clock[0]}, '{u}', {rng.randrange(1000)});",
                ))
        return out

    spawn_n = [0]

    def spawn(rules: str = ""):
        config = dict(base_config)
        if rules:
            # env-armed schedule for THIS process only: restarts get a
            # clean config, so a one-shot hang never re-arms
            config[cfg.FAULT_INJECTION_RULES] = rules
        spawn_n[0] += 1
        port_file = os.path.join(work, f"port_{spawn_n[0]}")
        env = dict(
            os.environ,
            KSQL_CHAOS_SERVE=json.dumps({
                "config": config, "command_log": wal,
                "port_file": port_file, "dump_file": dump_file,
            }),
            JAX_PLATFORMS="cpu",
        )
        log = open(os.path.join(work, f"serve_{spawn_n[0]}.log"), "w")
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--serve"],
            env=env, stdout=log, stderr=subprocess.STDOUT,
        )
        deadline = time.time() + 180
        while not os.path.exists(port_file):
            if proc.poll() is not None:
                raise RuntimeError(
                    f"--serve child died at boot (round {spawn_n[0]})"
                )
            if time.time() > deadline:
                proc.kill()
                raise RuntimeError("--serve child never bound a port")
            time.sleep(0.05)
        with open(port_file) as f:
            return proc, f"http://127.0.0.1:{int(f.read())}"

    def post(url, stmt, timeout=10.0):
        req = urllib.request.Request(
            url + "/ksql", data=json.dumps({"ksql": stmt}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status

    def scrape_replay_window(url):
        try:
            with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
                text = r.read().decode()
        except Exception:  # noqa: BLE001 — metrics must not fail the boot
            return None
        total = 0.0
        for line in text.splitlines():
            if line.startswith("ksql_query_recovery_replayed_rows_total{"):
                total += float(line.rsplit(" ", 1)[1])
        return total

    def scrape_replay_events(url):
        """True if any query's progress timeline carries a
        ``changelog.replay`` recovery event — per-restart evidence the
        tail was applied (each round is a fresh process, so the final
        dump's processing log only covers the last boot)."""
        try:
            with urllib.request.urlopen(
                url + "/healthcheck", timeout=10
            ) as r:
                per_q = json.load(r)["details"]["queries"]["perQuery"]
            for qid in per_q:
                with urllib.request.urlopen(
                    url + f"/query-lag/{qid}", timeout=10
                ) as r:
                    body = json.load(r)
                if any(ev.get("kind") == "changelog.replay"
                       for ev in body.get("events", [])):
                    return True
        except Exception:  # noqa: BLE001 — evidence scrape, not the soak
            pass
        return False

    def journal_forensics():
        """(intact frames, torn journals) across the checkpoint dir —
        read BETWEEN processes, straight off the killed image."""
        frames = torn = 0
        if os.path.isdir(ckpt):
            for fn in os.listdir(ckpt):
                if fn.endswith(".changelog"):
                    fs, _, t = read_frames(os.path.join(ckpt, fn))
                    frames += len(fs)
                    torn += bool(t)
        return frames, torn

    per_round = max(8, int(rate * seconds) // 80)
    kill_classes = ["mid-tick", "mid-checkpoint-save",
                    "mid-changelog-append"]
    n_crashes = 0
    replay_windows = []
    saw_replay_event = False
    frames_seen = 0
    torn_after_append_kill = 0
    insert_failures = 0
    try:
        for rnd, kill_class in enumerate(kill_classes):
            rules = ""
            if kill_class == "mid-checkpoint-save":
                rules = (
                    f"checkpoint.save:hang:count=1,"
                    f"after={1 + rng.randrange(2)}"
                )
            elif kill_class == "mid-changelog-append":
                rules = (
                    f"changelog.append:hang:count=1,"
                    f"after={2 + rng.randrange(3)}"
                )
            proc, url = spawn(rules)
            try:
                if rnd == 0:
                    for stmt in CRASH_SOURCES + CRASH_CARRIERS:
                        if post(stmt=stmt, url=url) != 200:
                            problems.append(f"DDL rejected: {stmt}")
                else:
                    w = scrape_replay_window(url)
                    if w is not None:
                        replay_windows.append(w)
                    saw_replay_event |= scrape_replay_events(url)
                consec_fail = 0
                for topic, ts, stmt in next_inserts(per_round):
                    try:
                        if post(url, stmt, timeout=3.0) == 200:
                            acked.append((topic, ts))
                            consec_fail = 0
                        else:
                            insert_failures += 1
                            consec_fail += 1
                    except Exception:  # noqa: BLE001 — unACKed: the row
                        insert_failures += 1  # is NOT owed to the sink
                        consec_fail += 1
                    if consec_fail >= 2:
                        # the armed hang wedged the engine lock; it stays
                        # wedged until the SIGKILL — stop burning timeouts
                        break
                    time.sleep(rng.uniform(0.0, 0.02))
                # mid-tick: kill inside the processing backlog; hang
                # classes: give the armed one-shot wedge time to engage
                time.sleep(
                    rng.uniform(0.05, 0.5) if kill_class == "mid-tick"
                    else 1.5
                )
            finally:
                proc.send_signal(signal.SIGKILL)
                proc.wait(timeout=30)
            n_crashes += 1
            f_, t_ = journal_forensics()
            frames_seen += f_
            if kill_class == "mid-changelog-append":
                torn_after_append_kill += t_
        # final clean round: restart, drain, SIGTERM -> parity dump
        proc, url = spawn()
        try:
            w = scrape_replay_window(url)
            if w is not None:
                replay_windows.append(w)
            saw_replay_event |= scrape_replay_events(url)
            for topic, ts, stmt in next_inserts(per_round):
                try:
                    if post(url, stmt, timeout=30.0) == 200:
                        acked.append((topic, ts))
                except Exception:  # noqa: BLE001
                    insert_failures += 1
            proc.send_signal(signal.SIGTERM)
            proc.wait(timeout=120)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)
        with open(dump_file) as f:
            dump = json.load(f)

        # ---- invariant: every ACKed INSERT is in the dumped sources
        from collections import Counter

        for topic in CRASH_SRC_TOPICS:
            want = Counter(ts for t, ts in acked if t == topic)
            have = Counter(r[2] for r in dump["topics"].get(topic, []))
            missing = want - have
            if missing:
                problems.append(
                    f"{topic}: {sum(missing.values())} ACKed rows lost "
                    f"(first {sorted(missing)[:3]})"
                )

        # ---- crash-free oracle twin fed the DUMPED source records
        # (ground truth of what entered the log, extras included)
        eo = KsqlEngine(KsqlConfig({cfg.RUNTIME_BACKEND: "oracle"}))
        try:
            for stmt in CRASH_SOURCES + CRASH_CARRIERS:
                eo.execute_sql(stmt)
            src = [
                (t, r) for t in CRASH_SRC_TOPICS
                for r in dump["topics"].get(t, [])
            ]
            src.sort(key=lambda tr: tr[1][2])  # global ROWTIME order
            for topic, (key, value, ts, _w) in src:
                eo.broker.topic(topic).produce(
                    Record(key=key, value=value, timestamp=ts)
                )
            eo.run_until_quiescent()
            dupes_total = 0
            for sink in CRASH_SINKS:
                def _ms(rows):
                    out: dict = {}
                    for k, v, ts, w in rows:
                        key = (k, v, ts, tuple(w) if w else None)
                        out[key] = out.get(key, 0) + 1
                    return out

                mine = _ms(dump["topics"].get(sink, []))
                ref = _ms([
                    [r.key, r.value, r.timestamp,
                     list(r.window) if r.window else None]
                    for r in eo.broker.topic(sink).all_records()
                ])
                lost = {
                    k: n - mine.get(k, 0) for k, n in ref.items()
                    if n > mine.get(k, 0)
                }
                dupes = sum(
                    n - ref.get(k, 0) for k, n in mine.items()
                    if n > ref.get(k, 0)
                )
                dupes_total += dupes
                if lost:
                    problems.append(
                        f"{sink}: {sum(lost.values())} rows LOST vs the "
                        f"crash-free twin (first "
                        f"{sorted(lost)[:2]})"
                    )
            # effectively-once: dupes bounded by one in-flight tick per
            # crash, never proportional to the feed
            if dupes_total > n_crashes * 8:
                problems.append(
                    f"{dupes_total} duplicate sink rows across "
                    f"{n_crashes} crashes — beyond the in-flight-tick "
                    f"fence bound"
                )
        finally:
            eo.shutdown()

        # ---- replay windows: ticks-since-last-checkpoint, never the
        # whole batch (the feed is hundreds of rows by the last restart)
        if replay_windows and max(replay_windows) > 150:
            problems.append(
                f"recovery replay window hit {max(replay_windows):.0f} "
                f"rows — whole-batch territory, the changelog tail did "
                f"not shrink it"
            )
        if frames_seen < 1:
            problems.append(
                "no intact changelog frames ever observed post-kill — "
                "the journal never engaged"
            )
        if torn_after_append_kill < 1:
            problems.append(
                "mid-changelog-append kill left no torn tail — the "
                "fault schedule never engaged"
            )
        # the recovery after the torn-tail kill must have truncated it
        _, torn_now = journal_forensics()
        # (the FINAL image was cleanly checkpointed: journals truncated)
        if torn_now:
            problems.append("journal still torn after a clean shutdown")
        # at least one restart must have recovered THROUGH the journal
        # (a kill can land exactly on a rotation boundary, so any single
        # restart may legitimately find an empty tail — but not all of
        # them while the feed was live)
        plog_keys = [k for k, _ in dump.get("plog", [])]
        if not (saw_replay_event or any(w > 0 for w in replay_windows)
                or any(k.startswith("changelog.replay:")
                       for k in plog_keys)):
            problems.append(
                "no restart ever replayed a changelog tail "
                f"(plog categories: {sorted(set(plog_keys))[:8]})"
            )
        ok = not problems
        msg = (
            f"acked={len(acked)} crashes={n_crashes} "
            f"frames_seen={frames_seen} torn_seen={torn_after_append_kill} "
            f"replay_windows={[int(w) for w in replay_windows]} "
            f"replay_event={saw_replay_event} "
            f"insert_failures={insert_failures}"
        )
        if problems:
            msg += " | " + "; ".join(problems)
        if verbose:
            print(("PASS " if ok else "FAIL ") + f"seed={seed} " + msg)
        return {"ok": ok, "message": msg, "acked": len(acked),
                "crashes": n_crashes}
    finally:
        shutil.rmtree(work, ignore_errors=True)


def main(argv=None) -> int:
    if "--serve" in (argv if argv is not None else sys.argv[1:]):
        # crash-soak child: everything it needs rides $KSQL_CHAOS_SERVE
        return crash_serve()
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seconds", type=float, default=10.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--backend", default="oracle",
                    choices=["oracle", "device", "device-only"])
    ap.add_argument("--rate", type=int, default=200)
    ap.add_argument("--corrupt", action="store_true",
                    help="add corrupt-mode serde.deserialize faults and "
                         "assert no SILENT loss (every skipped poison "
                         "record lands in the processing log)")
    ap.add_argument("--watch", action="store_true",
                    help="poll the health watchdog's /alerts view during "
                         "the soak and fail on any STALLED query that has "
                         "not recovered by convergence")
    ap.add_argument("--hang", action="store_true",
                    help="arm hang-mode faults in one query's tick body "
                         "under ksql.query.tick.timeout.ms and assert "
                         "deadline-killed ticks recover while the sibling "
                         "query keeps advancing (no head-of-line blocking)")
    ap.add_argument("--rescale", action="store_true",
                    help="force grow/shrink mesh cutovers on distributed "
                         "queries under the raise/delay/hang fault mix and "
                         "assert no lost rows, no terminal ERROR from the "
                         "rescale, and bounded gap markers per push session")
    ap.add_argument("--fanout", action="store_true",
                    help="kill/hang the ONE shared push-registry pipeline "
                         "under ~50 filtered taps; assert a single shared "
                         "pipeline, no terminal taps within the retry "
                         "budget, and no lost rows beyond gap-marked spans")
    ap.add_argument("--taps", type=int, default=50,
                    help="tap count for --fanout")
    ap.add_argument("--overload", action="store_true",
                    help="flood a live KsqlServer (producer burst + tap "
                         "storm + transient-query storm) under a tight "
                         "HBM budget; assert the process survives, sheds "
                         "are real 429s, >=1 action engages and all clear "
                         "post-flood, laggard taps get terminal overload "
                         "markers, and persistent sinks match a "
                         "fault-free twin (runs two seeds)")
    ap.add_argument("--crash", action="store_true",
                    help="SIGKILL a live KsqlServer subprocess at "
                         "randomized points (mid-tick, mid-checkpoint-"
                         "save, mid-changelog-append), restart on the "
                         "same dirs, and assert effectively-once sink "
                         "parity vs a crash-free oracle twin: zero "
                         "ACKed rows lost, dupes bounded by one "
                         "in-flight tick per crash, replay window = "
                         "ticks-since-last-checkpoint (runs two seeds)")
    ap.add_argument("--mesh", action="store_true",
                    help="shard-level fault domain: distributed "
                         "aggregation/join/window carriers under "
                         "randomized mesh faults incl. one targeted "
                         "single-shard hang; assert zero lost rows, >=1 "
                         "degraded-mesh cutover, no terminal ERROR, and "
                         "sink+pull parity vs a fault-free oracle twin")
    args = ap.parse_args(argv)
    if args.fanout:
        # both serving postures: fused residual kernel (with an injected
        # kernel failure proving the degrade-to-host contract) and the
        # host residual path outright
        res_fused = fanout_soak(seconds=args.seconds, seed=args.seed,
                                rate=args.rate, taps=args.taps, fused=True)
        res_host = fanout_soak(seconds=args.seconds, seed=args.seed,
                               rate=args.rate, taps=args.taps, fused=False)
        res = {"ok": res_fused["ok"] and res_host["ok"],
               "message": res_fused["message"] + " || " + res_host["message"],
               "fused": res_fused, "host": res_host}
    elif args.overload:
        # two seeds back to back: the acceptance bar for the overload
        # ladder is reproducibility, not one lucky flood
        res_a = overload_soak(seconds=args.seconds, seed=args.seed,
                              rate=args.rate)
        res_b = overload_soak(seconds=args.seconds, seed=args.seed + 1,
                              rate=args.rate)
        res = {"ok": res_a["ok"] and res_b["ok"],
               "message": res_a["message"] + " || " + res_b["message"],
               "seed_a": res_a, "seed_b": res_b}
    elif args.crash:
        # two seeds back to back: kill-9 recovery must be reproducible,
        # not one lucky interleaving (mirrors the --overload bar)
        res_a = run_crash(seconds=args.seconds, seed=args.seed,
                          rate=args.rate)
        res_b = run_crash(seconds=args.seconds, seed=args.seed + 1,
                          rate=args.rate)
        res = {"ok": res_a["ok"] and res_b["ok"],
               "message": res_a["message"] + " || " + res_b["message"],
               "seed_a": res_a, "seed_b": res_b}
    elif args.mesh:
        res = mesh_soak(seconds=args.seconds, seed=args.seed,
                        rate=args.rate)
    elif args.rescale:
        res = rescale_soak(seconds=args.seconds, seed=args.seed,
                           rate=args.rate)
    elif args.hang:
        res = hang_soak(seconds=args.seconds, seed=args.seed,
                        backend=args.backend, rate=args.rate)
    else:
        res = soak(seconds=args.seconds, seed=args.seed, backend=args.backend,
                   rate=args.rate, corrupt=args.corrupt, watch=args.watch)
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
