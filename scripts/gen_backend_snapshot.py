#!/usr/bin/env python
"""Regenerate tests/backend_snapshot.json — the pinned ahead-of-time
backend classification of the breadth golden-plan slice.

Like the golden plans themselves, a snapshot diff is a *compatibility
decision*: it means plans that used to run distributed/device now place
differently (or for different reasons).  Regenerate only when the
placement change is intentional, and review the diff:

    JAX_PLATFORMS=cpu python scripts/gen_backend_snapshot.py
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    from ksql_tpu.tools.golden_plans import (
        BREADTH_FILES,
        SNAPSHOT_PATH,
        classify_corpus,
    )

    snap = classify_corpus(BREADTH_FILES)
    with open(SNAPSHOT_PATH, "w") as f:
        json.dump(snap, f, indent=1, sort_keys=True)
        f.write("\n")
    n = sum(len(qs) for cases in snap.values() for qs in cases.values())
    print(f"wrote {SNAPSHOT_PATH}: {len(snap)} files, {n} plans")
    return 0


if __name__ == "__main__":
    sys.exit(main())
