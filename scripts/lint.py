#!/usr/bin/env python
"""graftlint CLI — run the repo's static-analysis rules over source trees.

Usage:
    python scripts/lint.py                  # lint the repo tree (default set)
    python scripts/lint.py path [path ...]  # lint specific files/dirs
    python scripts/lint.py --list-rules     # show rules + one-line docs
    python scripts/lint.py --rules donated-aliasing,jit-retrace ksql_tpu
    python scripts/lint.py --jobs 4         # parallel per-module analysis
    python scripts/lint.py --threads        # dump the concurrency map
    python scripts/lint.py --baseline lint_baseline.json            # diff-only
    python scripts/lint.py --baseline lint_baseline.json --write-baseline

Exit status: 0 when clean, 1 when any finding survives suppression (with
--baseline: when any finding is NEW relative to the audited snapshot).
Suppress a reviewed finding with ``# graftlint: disable=<rule>`` on (or
directly above) the flagged line; always pair it with a justification
comment.  tests/test_analysis.py runs the same default sweep in tier-1,
so a new violation fails the gate before it ships.

--threads prints the shared-state-race rule's per-module entrypoint map
(thread entrypoints, their call-graph reach, and every shared-state key
with its per-mutation guard) so reviewers can see the concurrency
surface at a glance.

--jobs N distributes the whole-program analysis over N processes: a
chunk-local summary pass, a merge, a second pass against the merged
table (the same two global passes the in-process path runs), then
parallel per-module rule checks.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the tier-1 sweep surface: every tree that feeds the running system
DEFAULT_PATHS = ["ksql_tpu", "scripts", "bench.py"]


def _fingerprint(finding, root: str) -> str:
    """Line numbers drift with every edit; rule + relative path + message
    (which embeds the offending names) is the stable identity an audited
    suppression snapshot can be keyed on."""
    rel = os.path.relpath(finding.path, root)
    return f"{finding.rule}|{rel}|{finding.message}"


def _lint_parallel(files, rule_names, jobs):
    from concurrent.futures import ProcessPoolExecutor
    from itertools import repeat

    from ksql_tpu.analysis.parallel_lint import (
        check_chunk,
        summarize_pass1,
        summarize_pass2,
    )

    if not files:
        return []  # nothing to lint: clean, same as the serial path
    chunks = [files[i::jobs] for i in range(jobs)]
    chunks = [c for c in chunks if c]
    need_summaries = rule_names is None or "donated-aliasing" in rule_names
    meta_all, summaries = {}, {}
    with ProcessPoolExecutor(max_workers=len(chunks)) as ex:
        if need_summaries:
            from ksql_tpu.analysis.rules_aliasing import DonatedAliasingRule

            for meta, summ in ex.map(summarize_pass1, chunks):
                meta_all.update(meta)
                summaries.update(summ)
            # iterate against the merged table to the same bounded
            # fixpoint as the in-process path: a taint chain spanning
            # chunks (leaf in one worker's files, caller in another's)
            # needs one merged pass per hop to propagate
            for _ in range(DonatedAliasingRule.MAX_PASSES - 1):
                before = dict(summaries)
                for summ in ex.map(
                    summarize_pass2, chunks, repeat(meta_all),
                    repeat(summaries),
                ):
                    summaries.update(summ)
                if summaries == before:
                    break
        # (non-aliasing rule sets need no resolution metadata: check_chunk
        # only feeds meta_all to the primed aliasing rule — parsing every
        # file in the parent just to build it would serialize the very
        # work --jobs distributes)
        findings = []
        for chunk_findings in ex.map(
            check_chunk, chunks, repeat(meta_all), repeat(summaries),
            repeat(sorted(rule_names) if rule_names else None),
        ):
            findings.extend(chunk_findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def _print_threads_report(files) -> None:
    from ksql_tpu.analysis import RaceAnalysis
    from ksql_tpu.analysis.lint import load_modules

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    any_out = False
    for module in load_modules(files):
        analysis = RaceAnalysis(module)
        rep = analysis.report()
        if not rep["entrypoints"]:
            continue
        any_out = True
        print(f"== {os.path.relpath(module.path, root)}")
        print("  entrypoints:")
        for ep in rep["entrypoints"]:
            print(
                f"    {ep['label']:<18} ({ep['kind']}) root={ep['root']} "
                f"line {ep['line']}, reaches {len(ep['reaches'])} fns"
            )
        if rep["shared"]:
            print("  shared state:")
            for key, info in rep["shared"].items():
                eps = ", ".join(info["entrypoints"])
                print(f"    {key:<34} [{eps}]")
                for mut in info["mutations"]:
                    print(
                        f"      L{mut['line']:<6} {mut['fn']:<28} "
                        f"guard={mut['guard']}"
                    )
        print()
    if not any_out:
        print("no thread entrypoints discovered in the linted tree")


def main(argv=None) -> int:
    from ksql_tpu.analysis import default_rules, expand_lint_paths, lint_paths

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files or directories "
                    f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--rules", help="comma-separated rule names to run "
                    "(default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="list rules and exit")
    ap.add_argument("--threads", action="store_true",
                    help="print the per-module thread-entrypoint / "
                    "shared-state map instead of linting")
    ap.add_argument("--jobs", type=int, default=1, metavar="N",
                    help="parallel per-module analysis over N processes")
    ap.add_argument("--baseline", metavar="FILE",
                    help="audited-suppression snapshot: only findings NOT "
                    "in FILE fail the run")
    ap.add_argument("--write-baseline", action="store_true",
                    help="(re)write --baseline FILE from the current "
                    "findings and exit 0")
    args = ap.parse_args(argv)
    if args.write_baseline and not args.baseline:
        ap.error("--write-baseline requires --baseline FILE")

    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name}: {r.doc}")
        return 0
    wanted = None
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.paths:
        missing = [p for p in args.paths if not os.path.exists(p)]
        if missing:
            print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
            return 2
        paths = args.paths
    else:
        paths = [p for p in (os.path.join(root, d) for d in DEFAULT_PATHS)
                 if os.path.exists(p)]
    files = expand_lint_paths(paths)

    if args.threads:
        _print_threads_report(files)
        return 0

    if args.jobs > 1:
        findings = _lint_parallel(files, wanted, args.jobs)
    else:
        findings = lint_paths(files, rules)

    if args.baseline and args.write_baseline:
        counts = {}
        for f in findings:
            fp = _fingerprint(f, root)
            counts[fp] = counts.get(fp, 0) + 1
        with open(args.baseline, "w", encoding="utf-8") as fh:
            json.dump({"fingerprints": counts}, fh, indent=2, sort_keys=True)
        print(f"baseline written: {len(findings)} finding(s) -> "
              f"{args.baseline}")
        return 0

    if args.baseline:
        try:
            with open(args.baseline, encoding="utf-8") as fh:
                budget = dict(json.load(fh).get("fingerprints", {}))
        except FileNotFoundError:
            print(f"baseline file not found: {args.baseline}",
                  file=sys.stderr)
            return 2
        fresh = []
        for f in findings:
            fp = _fingerprint(f, root)
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1  # audited: consumed from the snapshot
            else:
                fresh.append(f)
        for f in fresh:
            print(f.format())
        stale = sum(n for n in budget.values() if n > 0)
        if stale:
            print(f"note: {stale} baseline entr{'y' if stale == 1 else 'ies'}"
                  " no longer fire — consider --write-baseline",
                  file=sys.stderr)
        if fresh:
            print(f"{len(fresh)} NEW finding(s) vs baseline",
                  file=sys.stderr)
            return 1
        return 0

    for f in findings:
        print(f.format())
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
