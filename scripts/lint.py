#!/usr/bin/env python
"""graftlint CLI — run the repo's static-analysis rules over source trees.

Usage:
    python scripts/lint.py                  # lint the repo tree (default set)
    python scripts/lint.py path [path ...]  # lint specific files/dirs
    python scripts/lint.py --list-rules     # show rules + one-line docs
    python scripts/lint.py --rules donated-aliasing,trace-unsafe ksql_tpu

Exit status: 0 when clean, 1 when any finding survives suppression.
Suppress a reviewed finding with ``# graftlint: disable=<rule>`` on (or
directly above) the flagged line; always pair it with a justification
comment.  tests/test_analysis.py runs the same default sweep in tier-1,
so a new violation fails the gate before it ships.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: the tier-1 sweep surface: every tree that feeds the running system
DEFAULT_PATHS = ["ksql_tpu", "scripts", "bench.py"]


def main(argv=None) -> int:
    from ksql_tpu.analysis import default_rules, lint_paths

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files or directories "
                    f"(default: {' '.join(DEFAULT_PATHS)})")
    ap.add_argument("--rules", help="comma-separated rule names to run "
                    "(default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="list rules and exit")
    args = ap.parse_args(argv)

    rules = default_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name}: {r.doc}")
        return 0
    if args.rules:
        wanted = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = wanted - {r.name for r in rules}
        if unknown:
            print(f"unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.paths:
        missing = [p for p in args.paths if not os.path.exists(p)]
        if missing:
            print(f"no such path(s): {', '.join(missing)}", file=sys.stderr)
            return 2
        paths = args.paths
    else:
        paths = [p for p in (os.path.join(root, d) for d in DEFAULT_PATHS)
                 if os.path.exists(p)]
    findings = lint_paths(paths, rules)
    for f in findings:
        print(f.format())
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
