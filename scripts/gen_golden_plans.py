"""Regenerate the committed golden-plan corpus from the QTT corpus.

Usage: python scripts/gen_golden_plans.py [file-substring ...]
A plan diff under tests is a compatibility decision — regenerate only when
the plan format intentionally changes, and review the diff.
"""
import os
import sys
import concurrent.futures as cf

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ksql_tpu.tools.golden_plans import QTT_DIR, generate_file, write_golden  # noqa: E402


def main():
    pats = sys.argv[1:]
    files = sorted(
        f for f in os.listdir(QTT_DIR)
        if f.endswith(".json") and (not pats or any(p in f for p in pats))
    )
    total = 0
    with cf.ProcessPoolExecutor(max_workers=8) as pool:
        for fname, plans in pool.map(
            generate_file, (os.path.join(QTT_DIR, f) for f in files)
        ):
            if plans:
                write_golden(fname, plans)
                total += len(plans)
                print(f"{fname}: {len(plans)} plans")
    print(f"total: {total} golden plans")


if __name__ == "__main__":
    main()
