#!/usr/bin/env python
"""graftmem CLI — sweep the static device-memory model over plan corpora.

Usage:
    python scripts/memcheck.py                      # golden-corpus sweep
    python scripts/memcheck.py --files joins.json,having.json
    python scripts/memcheck.py --budget 268435456   # what-if admission gate
    python scripts/memcheck.py --shards 8           # per-shard/mesh pricing
    python scripts/memcheck.py --json               # machine-readable output
    python scripts/memcheck.py --top 10             # largest plans first

Walks every golden plan (golden_plans/<file>.json), builds the
construction-free ``analyze_only`` lowering probe, and prices its device
footprint with :mod:`ksql_tpu.analysis.mem_model` at the three report
points (at-creation / at-growth-cap / per-shard).  Plans that do not
lower to the device backend hold no HBM and are counted as skipped.

``--budget BYTES`` runs the admission gate as a what-if: every plan whose
per-shard at-creation footprint exceeds the budget is listed with its
dominant components, and the sweep exits 1 — the same verdict
``ksql.analysis.memory.budget.bytes`` + ``.strict`` would hand a CREATE.

tests/test_mem_model.py runs this sweep (tier-1), so the model, the
corpus, and this tool cannot drift apart silently.
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def sweep(files, capacity, store_capacity, n_shards, budget):
    """Price every golden plan; returns (results, skipped) where results
    is a list of per-plan dicts sorted largest-first."""
    from ksql_tpu.analysis import analyze_plan_memory
    from ksql_tpu.execution.steps import plan_from_json
    from ksql_tpu.functions.registry import FunctionRegistry
    from ksql_tpu.tools.golden_plans import GOLDEN_DIR

    registry = FunctionRegistry()
    results, skipped = [], 0
    for fname in files:
        with open(os.path.join(GOLDEN_DIR, fname)) as f:
            cases = json.load(f)
        for case, plans in sorted(cases.items()):
            for qid, pj in sorted(plans.items()):
                try:
                    report = analyze_plan_memory(
                        plan_from_json(pj), registry,
                        capacity=capacity, store_capacity=store_capacity,
                        n_shards=n_shards,
                        growth_budget_bytes=budget or None,
                    )
                except Exception:  # noqa: BLE001 — not device-lowerable:
                    skipped += 1  # no device memory to price
                    continue
                per_shard = report.per_shard_bytes("at_creation")
                dom = report.dominant("at_creation", include_transient=True)
                results.append({
                    "file": fname,
                    "case": case,
                    "query": qid,
                    "perShardBytes": per_shard,
                    "growthCapBytes": report.per_shard_bytes("at_growth_cap"),
                    "totalBytes": report.total_bytes("at_creation"),
                    "dominant": dom.name if dom is not None else "",
                    "overBudget": bool(budget and per_shard > budget),
                    "components": {
                        c.name: c.at_creation for c in report.components
                    },
                })
    results.sort(key=lambda r: -r["perShardBytes"])
    return results, skipped


def main(argv=None) -> int:
    from ksql_tpu.tools.golden_plans import GOLDEN_DIR

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--files", help="comma-separated corpus files "
                    "(default: every golden_plans/*.json)")
    ap.add_argument("--budget", type=int, default=0, metavar="BYTES",
                    help="what-if admission budget: list over-budget plans "
                    "and exit 1 (mirrors ksql.analysis.memory.budget.bytes)")
    ap.add_argument("--shards", type=int, default=1,
                    help="mesh size to price per-shard/total at (default 1)")
    ap.add_argument("--capacity", type=int, default=8192,
                    help="micro-batch capacity (ksql.batch.capacity)")
    ap.add_argument("--store-capacity", type=int, default=1 << 17,
                    help="state-store slots (ksql.state.slots)")
    ap.add_argument("--top", type=int, default=5,
                    help="largest plans to print (default 5)")
    ap.add_argument("--json", action="store_true",
                    help="dump the full sweep as JSON to stdout")
    args = ap.parse_args(argv)

    if args.files:
        files = [f.strip() for f in args.files.split(",") if f.strip()]
        missing = [
            f for f in files
            if not os.path.exists(os.path.join(GOLDEN_DIR, f))
        ]
        if missing:
            print(f"no such corpus file(s): {', '.join(missing)}",
                  file=sys.stderr)
            return 2
    else:
        files = sorted(
            f for f in os.listdir(GOLDEN_DIR) if f.endswith(".json")
        )

    results, skipped = sweep(
        files, args.capacity, args.store_capacity, max(1, args.shards),
        args.budget,
    )
    over = [r for r in results if r["overBudget"]]

    if args.json:
        json.dump({
            "files": files,
            "shards": max(1, args.shards),
            "budgetBytes": args.budget,
            "devicePlans": len(results),
            "skippedPlans": skipped,
            "overBudget": len(over),
            "plans": results,
        }, sys.stdout, indent=1)
        print()
    else:
        print(f"{len(results)} device plan(s) priced, {skipped} skipped "
              f"(not device-lowerable), shards={max(1, args.shards)}")
        for r in results[: max(0, args.top)]:
            print(
                f"  {r['perShardBytes']:>12} B/shard  "
                f"(growth-cap {r['growthCapBytes']}, dominant "
                f"{r['dominant'] or '-'})  {r['file']}:{r['case']}:"
                f"{r['query']}"
            )
        if args.budget:
            print(f"budget {args.budget} B/shard: {len(over)} plan(s) over")
            for r in over[:20]:
                print(
                    f"  OVER {r['perShardBytes']:>12} B  "
                    f"{r['file']}:{r['case']}:{r['query']} "
                    f"(dominant {r['dominant']})"
                )
    return 1 if over else 0


if __name__ == "__main__":
    sys.exit(main())
